"""Hook engine: pre/post-forward interception for weight tiering.

Parity target: reference ``src/accelerate/hooks.py`` (765 LoC): ``ModelHook``
protocol (43-98), ``add_hook_to_module`` (130), ``AlignDevicesHook`` (225-409),
``attach_align_device_hook[_on_blocks]`` (460/555), CPU-offload hooks (689-738).

TPU-native meaning: "device" for a hooked torch module is the *host staging tier*
(cpu RAM or disk memmap); the execution device is the TPU reached through the
jitted bridge.  ``AlignDevicesHook`` stages a block's weights from its tier into
host arrays before forward and back after — the jax device_put of the staged
block happens in the lowered apply.  For eager torch execution (no TPU in the
loop) the hooks behave exactly like the reference's.
"""

from __future__ import annotations

import functools
from typing import Mapping, Optional

import numpy as np

__all__ = [
    "ModelHook",
    "SequentialHook",
    "add_hook_to_module",
    "remove_hook_from_module",
    "remove_hook_from_submodules",
    "AlignDevicesHook",
    "CpuOffload",
    "UserCpuOffloadHook",
    "attach_align_device_hook",
    "LayerwiseCastingHook",
    "attach_layerwise_casting_hooks",
    "attach_align_device_hook_on_blocks",
    "named_module_tensors",
    "set_module_tensor_to_device",
]


def _send_to_torch_device(obj, device, skip_keys=None):
    """Recursively move torch tensors to a torch device, skipping Mapping keys
    in ``skip_keys`` at every nesting level (reference ``send_to_device``
    semantics, but torch-side: hooks run in the eager torch world — the jax
    transfer happens in the lowered bridge, not here)."""
    import torch

    if isinstance(skip_keys, str):
        skip_keys = [skip_keys]
    skip_keys = skip_keys or []
    if isinstance(obj, Mapping):
        return type(obj)(
            {
                k: (v if k in skip_keys else _send_to_torch_device(v, device, skip_keys))
                for k, v in obj.items()
            }
        )
    if isinstance(obj, (tuple, list)):
        from .utils.operations import honor_type

        # honor_type reconstructs namedtuples (type(obj)(generator) cannot).
        return honor_type(obj, (_send_to_torch_device(t, device, skip_keys) for t in obj))
    if isinstance(obj, torch.Tensor):
        return obj.to(device)
    return obj


class ModelHook:
    """Reference ``hooks.py:43-98`` protocol."""

    no_grad = False

    def init_hook(self, module):
        return module

    def pre_forward(self, module, *args, **kwargs):
        return args, kwargs

    def post_forward(self, module, output):
        return output

    def detach_hook(self, module):
        return module


class SequentialHook(ModelHook):
    """Compose several hooks (reference ``hooks.py SequentialHook``)."""

    def __init__(self, *hooks):
        self.hooks = hooks

    def init_hook(self, module):
        for hook in self.hooks:
            module = hook.init_hook(module)
        return module

    def pre_forward(self, module, *args, **kwargs):
        for hook in self.hooks:
            args, kwargs = hook.pre_forward(module, *args, **kwargs)
        return args, kwargs

    def post_forward(self, module, output):
        for hook in self.hooks:
            output = hook.post_forward(module, output)
        return output

    def detach_hook(self, module):
        for hook in self.hooks:
            module = hook.detach_hook(module)
        return module


def add_hook_to_module(module, hook: ModelHook, append: bool = False):
    """Wrap ``module.forward`` with the hook (reference ``hooks.py:130``)."""
    if append and getattr(module, "_hf_hook", None) is not None:
        old_hook = module._hf_hook
        remove_hook_from_module(module)
        hook = SequentialHook(old_hook, hook)

    if hasattr(module, "_hf_hook") and hasattr(module, "_old_forward"):
        old_forward = module._old_forward
        if "GraphModuleImpl" in str(type(module)):
            # A recompile() while hooked replaced the class forward with the
            # edited graph's; wrap THAT, not the stale pre-edit capture.
            current = type(module).__dict__.get("forward")
            hooked = getattr(module, "_accelerate_hooked_forward", None)
            if current is not None and not (
                isinstance(current, staticmethod) and current.__func__ is hooked
            ):
                old_forward = current.__get__(module, type(module))
                module._old_forward = old_forward
    else:
        old_forward = module.forward
        module._old_forward = old_forward

    module = hook.init_hook(module)
    module._hf_hook = hook

    @functools.wraps(old_forward)
    def new_forward(*args, **kwargs):
        args, kwargs = module._hf_hook.pre_forward(module, *args, **kwargs)
        if module._hf_hook.no_grad:
            import torch

            with torch.no_grad():
                output = old_forward(*args, **kwargs)
        else:
            output = old_forward(*args, **kwargs)
        return module._hf_hook.post_forward(module, output)

    # torch.fx GraphModules regenerate `forward` on the CLASS at recompile();
    # an instance-level override would shadow every future recompile (freeze
    # the graph — reference hooks.py:178).  Assign on the class there.
    if "GraphModuleImpl" in str(type(module)):
        # staticmethod: a plain function on the class would be a descriptor
        # and re-bind the instance as a spurious first argument (new_forward
        # already closes over `module`).  Remember the hooked forward so
        # remove can tell whether a recompile() replaced it in the meantime.
        module._accelerate_hooked_forward = new_forward
        type(module).forward = staticmethod(new_forward)
    else:
        module.forward = new_forward
    return module


def remove_hook_from_module(module, recurse: bool = False):
    if hasattr(module, "_hf_hook"):
        module._hf_hook.detach_hook(module)
        delattr(module, "_hf_hook")
    if hasattr(module, "_old_forward"):
        if "GraphModuleImpl" in str(type(module)):
            # Only restore if OUR hooked forward is still installed — a
            # recompile() while hooked replaces the class forward with the
            # edited graph's, which must survive removal.
            current = type(module).__dict__.get("forward")
            hooked = getattr(module, "_accelerate_hooked_forward", None)
            if isinstance(current, staticmethod) and current.__func__ is hooked:
                type(module).forward = module._old_forward
            if hooked is not None:
                delattr(module, "_accelerate_hooked_forward")
        else:
            module.forward = module._old_forward
        delattr(module, "_old_forward")
    if recurse:
        for child in module.children():
            remove_hook_from_module(child, recurse=True)
    return module


def remove_hook_from_submodules(module):
    remove_hook_from_module(module)
    for child in module.children():
        remove_hook_from_submodules(child)


def named_module_tensors(module, include_buffers: bool = True, recurse: bool = False):
    for name, param in module.named_parameters(recurse=recurse):
        yield name, param
    if include_buffers:
        for name, buf in module.named_buffers(recurse=recurse):
            yield name, buf


def set_module_tensor_to_device(
    module,
    tensor_name: str,
    device,
    value=None,
    dtype=None,
    tied_params_map: Optional[dict] = None,
    tied_key=None,
):
    """Move/replace one tensor of a torch module (reference
    ``utils/modeling.py set_module_tensor_to_device``).

    ``tied_params_map``/``tied_key``: dedup storage for tied parameters
    (reference ``big_modeling.py:410-424``): when the map already holds a
    materialized tensor for ``(tied_key, device)``, that tensor is REUSED (the
    new Parameter shares its storage — no second allocation); otherwise the
    freshly materialized tensor is recorded so later tied siblings reuse it.
    """
    import torch

    if "." in tensor_name:
        splits = tensor_name.split(".")
        for split in splits[:-1]:
            module = getattr(module, split)
        tensor_name = splits[-1]
    is_buffer = tensor_name in module._buffers
    old = module._buffers[tensor_name] if is_buffer else module._parameters[tensor_name]

    cached = None
    if tied_params_map is not None and tied_key is not None:
        cached = tied_params_map.setdefault(tied_key, {}).get(str(device))
    if cached is not None:
        new_tensor = cached
    elif value is not None:
        if isinstance(value, np.ndarray) or not isinstance(value, torch.Tensor):
            arr = np.asarray(value)
            if arr.dtype.name == "bfloat16":  # ml_dtypes bfloat16 -> torch view
                value = torch.from_numpy(arr.view(np.uint16).copy()).view(torch.bfloat16)
            else:
                if not arr.flags.writeable:
                    arr = arr.copy()  # read-only views make torch warn
                value = torch.as_tensor(arr)
        if (
            old is not None
            and tuple(old.shape) != tuple(value.shape)
            and old.numel() == value.numel()
            and (old.dim() == 0 or value.dim() == 0)
        ):
            # Scalar buffers (e.g. num_batches_tracked) round-trip through the
            # npz/safetensors path as shape (1,); size-1 rank mismatches are a
            # serialization artifact, not a real shape error.
            value = value.reshape(tuple(old.shape))
        if old is not None and tuple(old.shape) != tuple(value.shape):
            raise ValueError(
                f'Trying to set a tensor of shape {tuple(value.shape)} in "{tensor_name}" '
                f"whose shape is {tuple(old.shape)}; shapes must match exactly "
                "(reference set_module_tensor_to_device contract)."
            )
        if dtype is not None and (value.is_floating_point() or value.is_complex()):
            # Reference contract: int/uint/bool tensors (e.g. BatchNorm's
            # num_batches_tracked counter) keep their dtype when a float
            # target dtype is given.
            value = value.to(dtype)
        new_tensor = value.to(device)
    else:
        new_tensor = old.to(device)
    if cached is None and tied_params_map is not None and tied_key is not None and str(device) != "meta":
        tied_params_map[tied_key][str(device)] = new_tensor
    if is_buffer:
        module._buffers[tensor_name] = new_tensor
    else:
        requires_grad = (
            bool(old.requires_grad) if old is not None else False
        ) and new_tensor.is_floating_point()
        # torch.nn.Parameter shares the data storage — tied reuse stays a
        # single allocation per device.
        module._parameters[tensor_name] = torch.nn.Parameter(new_tensor, requires_grad=requires_grad)


class AlignDevicesHook(ModelHook):
    """Stage a module's weights in before forward, release after.

    Parity: reference ``hooks.py:225-409``.  ``execution_device`` here is a host
    staging device ("cpu") — the TPU transfer happens inside the lowered apply —
    or a torch device for eager execution.  ``offload=True`` keeps weights in a
    ``weights_map`` (memmap/safetensors) and materializes them per forward.
    """

    def __init__(
        self,
        execution_device=None,
        offload: bool = False,
        io_same_device: bool = False,
        weights_map: Optional[Mapping] = None,
        offload_buffers: bool = False,
        place_submodules: bool = False,
        skip_keys=None,
        tied_params_map: Optional[dict] = None,
        tied_names: Optional[Mapping] = None,
    ):
        self.execution_device = execution_device or "cpu"
        self.offload = offload
        self.io_same_device = io_same_device
        self.weights_map = weights_map
        self.offload_buffers = offload_buffers
        self.place_submodules = place_submodules
        # Input/output pytree keys that must NOT be moved between devices
        # (reference hooks.py:253 ``skip_keys`` — e.g. a past_key_values cache
        # the caller wants to keep where it is).
        self.skip_keys = skip_keys
        # Tied-parameter dedup (reference big_modeling.py:410-424):
        # ``tied_names`` maps a full weight name -> its group's canonical key;
        # ``tied_params_map[canonical][device]`` holds the one materialized
        # tensor every tied sibling shares on that device.
        self.tied_params_map = tied_params_map
        self.tied_names = tied_names or {}
        self._tied_added: set = set()
        self.original_devices = {}
        self.input_device = None
        # Weight keys of upcoming block(s), queued on the native prefetch pool
        # at this block's pre_forward (wired by wire_sequential_prefetch).
        self.prefetch_next: list = []

    def _tied_key(self, full_name):
        return self.tied_names.get(full_name) if self.tied_params_map is not None else None

    def init_hook(self, module):
        if self.offload:
            # Buffers stay resident unless offload_buffers=True (reference
            # hooks.py AlignDevicesHook semantics).
            self.original_devices = {
                name: p.device
                for name, p in named_module_tensors(
                    module, include_buffers=self.offload_buffers, recurse=self.place_submodules
                )
            }
            for name, _ in named_module_tensors(
                module, include_buffers=self.offload_buffers, recurse=self.place_submodules
            ):
                set_module_tensor_to_device(module, name, "meta")
        elif self.execution_device not in (None, "cpu"):
            prefix = getattr(module, "_hook_weights_prefix", "")
            for name, _ in named_module_tensors(module, recurse=self.place_submodules):
                # Resident placement: tied weights materialize ONCE per device
                # across all hooked modules (persistent dedup).
                set_module_tensor_to_device(
                    module,
                    name,
                    self.execution_device,
                    tied_params_map=self.tied_params_map,
                    tied_key=self._tied_key(prefix + name),
                )
        return module

    def pre_forward(self, module, *args, **kwargs):
        if self.io_same_device and args:
            import torch

            first = next((a for a in args if isinstance(a, torch.Tensor)), None)
            self.input_device = first.device if first is not None else None
        if self.offload:
            if self.prefetch_next and hasattr(self.weights_map, "prefetch"):
                # Queue the NEXT block's disk reads before staging this block's
                # weights: the pool's worker threads overlap that IO with this
                # block's copy + compute (vs the reference's per-block blocking
                # load, hooks.py:328-371).
                self.weights_map.prefetch(self.prefetch_next)
            prefix = getattr(module, "_hook_weights_prefix", "")
            for name, _ in named_module_tensors(
                module, include_buffers=self.offload_buffers, recurse=self.place_submodules
            ):
                tied_key = self._tied_key(prefix + name)
                already = (
                    tied_key is not None
                    and str(self.execution_device) in self.tied_params_map.get(tied_key, {})
                )
                # A tied sibling already materialized this weight on the
                # execution device: skip the weights_map load entirely.
                value = None if already else self.weights_map[prefix + name]
                if tied_key is not None and not already:
                    self._tied_added.add(tied_key)
                set_module_tensor_to_device(
                    module,
                    name,
                    self.execution_device,
                    value=value,
                    tied_params_map=self.tied_params_map,
                    tied_key=tied_key,
                )
        if self.skip_keys is not None and self.execution_device not in (None, "cpu"):
            args = _send_to_torch_device(args, self.execution_device, self.skip_keys)
            kwargs = _send_to_torch_device(kwargs, self.execution_device, self.skip_keys)
        return args, kwargs

    def post_forward(self, module, output):
        if self.offload:
            for name, _ in named_module_tensors(
                module, include_buffers=self.offload_buffers, recurse=self.place_submodules
            ):
                set_module_tensor_to_device(module, name, "meta")
            # Free the tied tensors THIS hook materialized (reference
            # hooks.py:386-397): siblings inside this forward reused them;
            # keeping them would pin the dedup copy in RAM past the block.
            if self.tied_params_map is not None:
                for key in self._tied_added:
                    self.tied_params_map.get(key, {}).pop(str(self.execution_device), None)
                self._tied_added.clear()
        if self.io_same_device and self.input_device is not None:
            output = _send_to_torch_device(output, self.input_device, self.skip_keys)
        return output

    def detach_hook(self, module):
        if self.offload:
            prefix = getattr(module, "_hook_weights_prefix", "")
            for name, device in self.original_devices.items():
                if str(device) != "meta" and self.weights_map is not None:
                    set_module_tensor_to_device(
                        module, name, device, value=self.weights_map.get(prefix + name)
                    )
        return module


def attach_align_device_hook(
    module,
    execution_device=None,
    offload: bool = False,
    weights_map: Optional[Mapping] = None,
    offload_buffers: bool = False,
    module_name: str = "",
    skip_keys=None,
    tied_params_map: Optional[dict] = None,
    tied_names: Optional[Mapping] = None,
    preload_module_classes: Optional[list] = None,
):
    """Attach AlignDevicesHooks to every leaf module holding weights (reference
    ``hooks.py:460``).

    ``preload_module_classes``: class names whose WHOLE subtree materializes at
    that module's own pre-forward (``place_submodules=True``) — required when a
    forward uses child weights functionally (``F.linear(x, self.sub.weight)``)
    so the child's forward (and its hook) never runs.
    """
    preload = (
        preload_module_classes is not None
        and type(module).__name__ in preload_module_classes
    )
    directs = list(named_module_tensors(module, recurse=preload))
    if directs:
        module._hook_weights_prefix = f"{module_name}." if module_name else ""
        add_hook_to_module(
            module,
            AlignDevicesHook(
                execution_device=execution_device,
                offload=offload,
                weights_map=weights_map,
                offload_buffers=offload_buffers,
                place_submodules=preload,
                skip_keys=skip_keys,
                tied_params_map=tied_params_map,
                tied_names=tied_names,
            ),
            append=True,
        )
    if preload:
        return  # the whole subtree is owned by this module's hook
    for child_name, child in module.named_children():
        full = f"{module_name}.{child_name}" if module_name else child_name
        attach_align_device_hook(
            child,
            execution_device=execution_device,
            offload=offload,
            weights_map=weights_map,
            offload_buffers=offload_buffers,
            module_name=full,
            skip_keys=skip_keys,
            tied_params_map=tied_params_map,
            tied_names=tied_names,
            preload_module_classes=preload_module_classes,
        )


def attach_align_device_hook_on_blocks(
    module,
    execution_device=None,
    offload=None,
    weights_map: Optional[Mapping] = None,
    offload_buffers: bool = False,
    module_name: str = "",
    skip_keys=None,
    tied_params_map: Optional[dict] = None,
    tied_names: Optional[Mapping] = None,
    preload_module_classes: Optional[list] = None,
):
    """Per-block variant driven by a device map (reference ``hooks.py:555``).

    ``execution_device``/``offload`` may be dicts keyed by module path.
    """
    if not isinstance(execution_device, Mapping):
        execution_device = {module_name: execution_device}
    if not isinstance(offload, Mapping):
        offload = {module_name: bool(offload)}

    if module_name in execution_device:
        if offload.get(module_name, False):
            module._hook_weights_prefix = f"{module_name}." if module_name else ""
            attach_align_device_hook(
                module,
                execution_device=execution_device[module_name],
                offload=True,
                weights_map=weights_map,
                offload_buffers=offload_buffers,
                module_name=module_name,
                skip_keys=skip_keys,
                tied_params_map=tied_params_map,
                tied_names=tied_names,
                preload_module_classes=preload_module_classes,
            )
        else:
            module._hook_weights_prefix = f"{module_name}." if module_name else ""
            add_hook_to_module(
                module,
                AlignDevicesHook(
                    execution_device[module_name],
                    io_same_device=not module_name,
                    skip_keys=skip_keys,
                    tied_params_map=tied_params_map,
                    tied_names=tied_names,
                ),
            )
        return
    for child_name, child in module.named_children():
        full = f"{module_name}.{child_name}" if module_name else child_name
        attach_align_device_hook_on_blocks(
            child,
            execution_device=execution_device,
            offload=offload,
            weights_map=weights_map,
            offload_buffers=offload_buffers,
            module_name=full,
            skip_keys=skip_keys,
            tied_params_map=tied_params_map,
            tied_names=tied_names,
            preload_module_classes=preload_module_classes,
        )


def _iter_hooks(hook):
    if isinstance(hook, SequentialHook):
        yield from hook.hooks
    elif hook is not None:
        yield hook


def wire_sequential_prefetch(model, depth: int = 1) -> int:
    """Chain offloading AlignDevicesHooks so each block's pre_forward queues the
    next ``depth`` blocks' weight files on the prefetch pool.

    Forward order is approximated by registration (module-tree) order — the
    order attach_align_device_hook walks, which matches execution for the
    sequential block structure device maps describe.  Returns the number of
    hooks wired."""
    hooked = []
    for _, mod in model.named_modules():
        for h in _iter_hooks(getattr(mod, "_hf_hook", None)):
            if isinstance(h, AlignDevicesHook) and h.offload:
                prefix = getattr(mod, "_hook_weights_prefix", "")
                keys = [
                    prefix + name
                    for name, _ in named_module_tensors(
                        mod, include_buffers=h.offload_buffers, recurse=h.place_submodules
                    )
                ]
                hooked.append((h, keys))
    for i, (h, _) in enumerate(hooked):
        nxt: list = []
        for j in range(i + 1, min(i + 1 + depth, len(hooked))):
            nxt.extend(hooked[j][1])
        h.prefetch_next = nxt
    return len(hooked)


class CpuOffload(ModelHook):
    """Move module to execution device on forward; previous module back to CPU
    (reference ``hooks.py:689``)."""

    def __init__(self, execution_device=None, prev_module_hook: Optional["UserCpuOffloadHook"] = None):
        self.execution_device = execution_device or "cpu"
        self.prev_module_hook = prev_module_hook

    def init_hook(self, module):
        return module.to("cpu")

    def pre_forward(self, module, *args, **kwargs):
        if self.prev_module_hook is not None:
            self.prev_module_hook.offload()
        module.to(self.execution_device)
        return args, kwargs


class UserCpuOffloadHook:
    """User handle pairing a model with its CpuOffload hook (reference
    ``hooks.py:720``)."""

    def __init__(self, model, hook: CpuOffload):
        self.model = model
        self.hook = hook

    def offload(self):
        self.model.to("cpu")

    def remove(self):
        remove_hook_from_module(self.model)


class LayerwiseCastingHook(ModelHook):
    """Store a layer's weights in a low-precision dtype, upcast to the compute
    dtype around forward (reference ``hooks.py:741-765``).

    TPU meaning: fp8/bf16 *storage* halves the host-RAM/HBM footprint of a
    dispatched model while matmuls still run in the compute dtype — the same
    recipe as the fp8 weight-only path in ``ops/fp8.py``, applied at the torch
    module boundary.
    """

    def __init__(self, storage_dtype, compute_dtype, non_blocking: bool = False):
        self.storage_dtype = storage_dtype
        self.compute_dtype = compute_dtype
        self.non_blocking = non_blocking

    def _cast(self, module, dtype):
        # Direct tensors only — module.to() recurses into children, which would
        # re-cast submodules the skip list excluded.
        for p in module.parameters(recurse=False):
            if p.is_floating_point():
                p.data = p.data.to(dtype, non_blocking=self.non_blocking)
        for name, b in module._buffers.items():
            if b is not None and b.is_floating_point():
                module._buffers[name] = b.to(dtype, non_blocking=self.non_blocking)
        return module

    def init_hook(self, module):
        return self._cast(module, self.storage_dtype)

    def pre_forward(self, module, *args, **kwargs):
        self._cast(module, self.compute_dtype)
        return args, kwargs

    def post_forward(self, module, output):
        self._cast(module, self.storage_dtype)
        return output

    def detach_hook(self, module):
        return self._cast(module, self.compute_dtype)


_DEFAULT_SKIP_CAST_PATTERNS = ("norm", "embed", "ln_", "layernorm")


def attach_layerwise_casting_hooks(
    module,
    storage_dtype,
    compute_dtype,
    skip_modules_pattern=_DEFAULT_SKIP_CAST_PATTERNS,
    skip_modules_classes=(),
    non_blocking: bool = False,
    _prefix: str = "",
):
    """Walk the module tree attaching :class:`LayerwiseCastingHook` to leaf
    modules with weights, skipping precision-sensitive ones (norms/embeddings
    by default) — reference ``big_modeling.py:653`` semantics."""
    import torch

    name = _prefix.rsplit(".", 1)[-1].lower()
    if (skip_modules_classes and isinstance(module, tuple(skip_modules_classes))) or (
        skip_modules_pattern and any(p in name for p in skip_modules_pattern)
    ):
        return
    has_own_params = any(True for _ in module.parameters(recurse=False))
    children = list(module.named_children())
    if has_own_params and not children:
        add_hook_to_module(
            module,
            LayerwiseCastingHook(storage_dtype, compute_dtype, non_blocking),
            append=True,
        )
        return
    if has_own_params:
        # Mixed node: cast its direct params too.
        add_hook_to_module(
            module,
            LayerwiseCastingHook(storage_dtype, compute_dtype, non_blocking),
            append=True,
        )
    for child_name, child in children:
        attach_layerwise_casting_hooks(
            child,
            storage_dtype,
            compute_dtype,
            skip_modules_pattern,
            skip_modules_classes,
            non_blocking,
            _prefix=f"{_prefix}.{child_name}" if _prefix else child_name,
        )
