"""Pipelined inference — capability parity with reference ``inference.py``.

The reference's ``prepare_pippy`` (``inference.py:124-184``) splits a torch module
at auto-balanced points (``generate_device_map`` ``inference.py:31``), builds a
``torch.distributed.pipelining`` GPipe schedule, rank 0 feeds inputs and the last
rank yields outputs (``pippy_forward`` ``inference.py:99-121``).

TPU-native redesign: there are no per-rank processes to choreograph — the split is
a sharding.  ``prepare_pippy`` stacks the model's layers into ``pp``-sharded stages
and returns ONE jit-compiled forward that runs the GPipe microbatch schedule as a
``lax.scan`` (see ``parallel/pipeline.py``); outputs are global arrays, so the
reference's "optionally broadcast from last rank" knob is always-on for free.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax

from .state import AcceleratorState
from .utils.dataclasses import PipelineParallelPlugin

__all__ = ["prepare_pippy"]


def prepare_pippy(
    params: Any,
    config: Any = None,
    plugin: Optional[PipelineParallelPlugin] = None,
    *,
    num_chunks: Optional[int] = None,
    stage_fn: Optional[Callable] = None,
    jit: bool = True,
) -> Callable[[jax.Array], jax.Array]:
    """Build a pipelined forward callable.

    Two modes:
    - flagship model: ``prepare_pippy(llama_params, llama_config)`` -> a callable
      ``f(input_ids) -> logits`` pipelined over the mesh's ``pp`` axis;
    - generic: pass ``stage_fn(stage_params, acts) -> acts`` and stage-stacked
      ``params`` ([S, ...] leaves) to pipeline any per-stage body.

    ``num_chunks`` defaults to the pp degree (reference default: one chunk per
    process, ``inference.py:150``).
    """
    state = AcceleratorState()
    mesh = state.mesh
    if "pp" not in mesh.axis_names or mesh.shape["pp"] < 2:
        raise ValueError(
            "prepare_pippy needs a mesh with a pp axis of size >= 2 "
            f"(got {dict(zip(mesh.axis_names, mesh.devices.shape))}); configure "
            "ParallelismConfig(pp=...) on the AcceleratorState."
        )
    pp = plugin.pp_size if plugin is not None and plugin.pp_size > 1 else mesh.shape["pp"]
    # num_micro_batches=1 is the dataclass default, not an explicit request for a
    # degenerate single-chunk schedule — only honor it when > 1.
    plugin_chunks = plugin.num_micro_batches if plugin is not None and plugin.num_micro_batches > 1 else None
    chunks = num_chunks or plugin_chunks or pp

    from .parallel import pipeline as pl

    # Schedule resolution: an explicit plugin wins, else the state's pp_plugin
    # (the same config the training-side lowering reads).  The GENERIC
    # stage_fn mode only honors an EXPLICIT plugin: its params contract is
    # caller-stacked leaves whose leading dim must match the schedule
    # ([S] for gpipe, [S·v] for interleaved), so an ambient training plugin
    # must not silently reinterpret previously-valid [S]-stacked params.
    if stage_fn is not None:
        sched_src = plugin
    else:
        sched_src = plugin if plugin is not None else getattr(state, "pp_plugin", None)
    schedule = getattr(sched_src, "schedule", "gpipe") or "gpipe"
    virtual_stages = getattr(sched_src, "virtual_stages", 1) or 1

    if stage_fn is not None:
        def forward(x):
            return pl.pipeline_apply(
                stage_fn, params, x, num_micro_batches=chunks,
                schedule=schedule, virtual_stages=virtual_stages,
            )
    else:
        if config is None:
            raise ValueError("pass the model config for the flagship-model path")

        def forward(input_ids):
            return pl.pipeline_llama_apply(
                params, input_ids, config, num_stages=pp, num_micro_batches=chunks,
                schedule=schedule, virtual_stages=virtual_stages,
            )

    return jax.jit(forward) if jit else forward
