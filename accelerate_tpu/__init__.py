"""accelerate_tpu — a TPU-native training/inference framework.

Brand-new design with the capabilities of the reference HF Accelerate snapshot
(surveyed in SURVEY.md): one ``Accelerator`` façade over a jit-compiled JAX/XLA
train step, GSPMD sharding over a named device mesh instead of torch engine
wrappers, and net-new long-context (ring attention) support.
"""

__version__ = "0.1.0"

from .state import AcceleratorState, GradientState, PartialState
from .utils import (
    AutocastKwargs,
    DataLoaderConfiguration,
    DDPCommunicationHookType,
    DeepSpeedPlugin,
    DistributedDataParallelKwargs,
    DistributedInitKwargs,
    DistributedType,
    FullyShardedDataParallelPlugin,
    GradientAccumulationPlugin,
    GradScalerKwargs,
    InitProcessGroupKwargs,
    MixedPrecisionPolicy,
    ParallelismConfig,
    ProfileKwargs,
    ProjectConfiguration,
    SequenceParallelPlugin,
    TensorParallelPlugin,
    set_seed,
    synchronize_rng_states,
)

# Accelerator / data-loader / big-modeling symbols are appended to this namespace as
# their modules land (mirroring reference src/accelerate/__init__.py:16-50).


def __getattr__(name):
    # Lazy imports so `import accelerate_tpu` stays cheap and avoids cycles.
    if name in ("Accelerator", "JaxModel", "PreparedModel"):
        from . import accelerator

        return getattr(accelerator, name)
    if name in ("prepare_data_loader", "skip_first_batches", "DataLoaderShard", "DataLoaderDispatcher"):
        from . import data_loader

        return getattr(data_loader, name)
    if name == "find_executable_batch_size":
        from .utils.memory import find_executable_batch_size

        return find_executable_batch_size
    if name in ("make_train_step", "TrainStep", "DevicePrefetcher"):
        from . import pipeline

        return getattr(pipeline, name)
    if name == "is_rich_available":
        from .utils.imports import is_rich_available

        return is_rich_available
    if name in ("notebook_launcher", "debug_launcher"):
        from . import launchers

        return getattr(launchers, name)
    if name == "LocalSGD":
        from .local_sgd import LocalSGD

        return LocalSGD
    if name in ("init_empty_weights", "init_on_device", "infer_auto_device_map", "dispatch_model",
                "load_checkpoint_and_dispatch", "cpu_offload", "cpu_offload_with_hook",
                "disk_offload", "load_checkpoint_in_model"):
        from . import big_modeling

        return getattr(big_modeling, name)
    if name == "ring_attention":
        from .ops import ring_attention

        return ring_attention
    if name == "prepare_pippy":
        from .inference import prepare_pippy

        return prepare_pippy
    if name == "get_logger":
        from .logging import get_logger

        return get_logger
    if name in ("PreemptionGuard", "RetryPolicy", "retrying", "verify_checkpoint",
                "find_latest_complete", "CheckpointVerificationError"):
        from . import resilience

        return getattr(resilience, name)
    if name in ("ServingEngine", "ServingConfig", "AdmissionRejected", "ServingJournal"):
        from . import serving

        return getattr(serving, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
