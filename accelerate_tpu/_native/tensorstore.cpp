// Native tensor IO + background prefetch pool for the offload subsystem.
//
// Role in the framework: the reference delegates its native work to external
// binaries (torch.distributed C++, DeepSpeed kernels — SURVEY headline facts);
// our XLA runtime covers the compute path, and this library covers the *IO*
// path the reference leaves to Python: streaming offloaded weight shards
// (utils/offload.py .dat files, reference utils/offload.py:25-66) from disk /
// page cache into user buffers with a thread pool that overlaps the next
// block's read with the current block's compute (the reference's per-block
// blocking copy in AlignDevicesHook.pre_forward, hooks.py:328-371, is the
// anti-pattern this removes).
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the image).
//
// Build: g++ -O3 -march=native -shared -fPIC -pthread tensorstore.cpp
//        -o libtensorstore.so   (driven by utils/native_io.py at first use)

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// Chunk size for read/write loops: large enough to saturate NVMe queues,
// small enough to keep many files interleaving fairly.
constexpr size_t kChunk = 8u << 20;  // 8 MiB

int64_t file_size(const char* path) {
  struct stat st;
  if (::stat(path, &st) != 0) return -1;
  return static_cast<int64_t>(st.st_size);
}

int read_file_into(const char* path, void* out, uint64_t nbytes, uint64_t offset) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -1;
#ifdef POSIX_FADV_SEQUENTIAL
  ::posix_fadvise(fd, static_cast<off_t>(offset), static_cast<off_t>(nbytes),
                  POSIX_FADV_SEQUENTIAL);
#endif
  char* dst = static_cast<char*>(out);
  uint64_t done = 0;
  while (done < nbytes) {
    size_t want = nbytes - done < kChunk ? static_cast<size_t>(nbytes - done) : kChunk;
    ssize_t got = ::pread(fd, dst + done, want, static_cast<off_t>(offset + done));
    if (got < 0) {
      ::close(fd);
      return -1;
    }
    if (got == 0) break;  // EOF
    done += static_cast<uint64_t>(got);
  }
  ::close(fd);
  return done == nbytes ? 0 : -1;
}

struct Entry {
  std::mutex m;
  std::condition_variable cv;
  enum State { kQueued, kLoading, kDone } state = kQueued;
  bool failed = false;
  std::vector<char> data;
};

struct Pool {
  std::mutex m;
  std::condition_variable cv;
  std::deque<std::string> queue;
  std::unordered_map<std::string, std::shared_ptr<Entry>> cache;
  std::vector<std::thread> workers;
  bool stopping = false;
  int pending = 0;

  explicit Pool(int n) {
    for (int i = 0; i < n; ++i) {
      workers.emplace_back([this] { this->run(); });
    }
  }

  ~Pool() {
    {
      std::lock_guard<std::mutex> lk(m);
      stopping = true;
    }
    cv.notify_all();
    for (auto& t : workers) t.join();
  }

  void run() {
    for (;;) {
      std::string path;
      std::shared_ptr<Entry> entry;
      {
        std::unique_lock<std::mutex> lk(m);
        cv.wait(lk, [this] { return stopping || !queue.empty(); });
        if (stopping && queue.empty()) return;
        path = std::move(queue.front());
        queue.pop_front();
        auto it = cache.find(path);
        if (it == cache.end()) {  // fetch() already consumed it synchronously
          --pending;
          continue;
        }
        entry = it->second;
        // Claim the entry while still holding the pool lock: a fetch() that
        // erases it after this point sees kLoading and waits instead of
        // duplicating the read.
        std::lock_guard<std::mutex> elk(entry->m);
        entry->state = Entry::kLoading;
      }
      int64_t sz = file_size(path.c_str());
      bool ok = sz >= 0;
      std::vector<char> buf;
      if (ok) {
        buf.resize(static_cast<size_t>(sz));
        ok = read_file_into(path.c_str(), buf.data(), static_cast<uint64_t>(sz), 0) == 0;
      }
      {
        std::lock_guard<std::mutex> lk(entry->m);
        entry->data = std::move(buf);
        entry->failed = !ok;
        entry->state = Entry::kDone;
      }
      entry->cv.notify_all();
      {
        std::lock_guard<std::mutex> lk(m);
        --pending;
      }
    }
  }
};

}  // namespace

extern "C" {

int ts_write(const char* path, const void* data, uint64_t nbytes) {
  int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -1;
  const char* src = static_cast<const char*>(data);
  uint64_t done = 0;
  while (done < nbytes) {
    size_t want = nbytes - done < kChunk ? static_cast<size_t>(nbytes - done) : kChunk;
    ssize_t put = ::write(fd, src + done, want);
    if (put < 0) {
      ::close(fd);
      return -1;
    }
    done += static_cast<uint64_t>(put);
  }
  ::close(fd);
  return 0;
}

int ts_read(const char* path, void* out, uint64_t nbytes, uint64_t offset) {
  return read_file_into(path, out, nbytes, offset);
}

int64_t ts_file_size(const char* path) { return file_size(path); }

void* ts_pool_create(int num_threads) {
  if (num_threads < 1) num_threads = 1;
  return new Pool(num_threads);
}

void ts_pool_destroy(void* pool) { delete static_cast<Pool*>(pool); }

// Queue an async full-file load. Idempotent per path until fetched.
int ts_pool_prefetch(void* pool, const char* path) {
  Pool* p = static_cast<Pool*>(pool);
  std::lock_guard<std::mutex> lk(p->m);
  if (p->cache.count(path)) return 0;
  p->cache.emplace(path, std::make_shared<Entry>());
  p->queue.emplace_back(path);
  ++p->pending;
  p->cv.notify_one();
  return 0;
}

// Queue a whole block's files in ONE call: newline-separated paths, one lock
// acquisition and one worker wake-up for the batch.  Per-call enqueues pay a
// scheduler round-trip each (notify_one preempts the caller on single-core
// hosts); a transformer block has ~10 tensors, so the batch removes ~9
// context-switch pairs per block.  Returns the number of paths enqueued.
int ts_pool_prefetch_many(void* pool, const char* paths) {
  Pool* p = static_cast<Pool*>(pool);
  int added = 0;
  {
    std::lock_guard<std::mutex> lk(p->m);
    const char* start = paths;
    for (const char* c = paths;; ++c) {
      if (*c == '\n' || *c == '\0') {
        if (c > start) {
          std::string path(start, static_cast<size_t>(c - start));
          if (!p->cache.count(path)) {
            p->cache.emplace(path, std::make_shared<Entry>());
            p->queue.emplace_back(std::move(path));
            ++p->pending;
            ++added;
          }
        }
        if (*c == '\0') break;
        start = c + 1;
      }
    }
  }
  if (added > 0) static_cast<Pool*>(pool)->cv.notify_all();
  return added;
}

// Blocking fetch: waits for the prefetched buffer (or reads synchronously if
// the path was never queued), copies min(nbytes, file size) into out, drops
// the cache entry. Returns bytes copied, or -1 on IO failure.
int64_t ts_pool_fetch(void* pool, const char* path, void* out, uint64_t nbytes) {
  Pool* p = static_cast<Pool*>(pool);
  std::shared_ptr<Entry> entry;
  {
    std::lock_guard<std::mutex> lk(p->m);
    auto it = p->cache.find(path);
    if (it != p->cache.end()) {
      entry = it->second;
      p->cache.erase(it);  // consume: worker seeing a missing entry skips it
    }
  }
  if (!entry) {
    int64_t sz = file_size(path);
    if (sz < 0) return -1;
    uint64_t n = nbytes < static_cast<uint64_t>(sz) ? nbytes : static_cast<uint64_t>(sz);
    if (read_file_into(path, out, n, 0) != 0) return -1;
    return static_cast<int64_t>(n);
  }
  std::unique_lock<std::mutex> lk(entry->m);
  if (entry->state == Entry::kQueued) {
    // The worker hasn't claimed it, and (with the cache entry erased above) it
    // never will — load synchronously.
    lk.unlock();
    int64_t sz = file_size(path);
    if (sz < 0) return -1;
    uint64_t n = nbytes < static_cast<uint64_t>(sz) ? nbytes : static_cast<uint64_t>(sz);
    if (read_file_into(path, out, n, 0) != 0) return -1;
    return static_cast<int64_t>(n);
  }
  entry->cv.wait(lk, [&] { return entry->state == Entry::kDone; });
  if (entry->failed) return -1;
  uint64_t n = nbytes < entry->data.size() ? nbytes : entry->data.size();
  std::memcpy(out, entry->data.data(), n);
  return static_cast<int64_t>(n);
}

int ts_pool_pending(void* pool) {
  Pool* p = static_cast<Pool*>(pool);
  std::lock_guard<std::mutex> lk(p->m);
  return p->pending;
}

}  // extern "C"
