"""Optimizer adapter — optax-backed, torch-optimizer-shaped.

Parity target: reference ``src/accelerate/optimizer.py`` (213 LoC,
``AcceleratedOptimizer``): no-op ``step``/``zero_grad`` while gradients are
accumulating, scaler integration, lazy XLA grad all-reduce at step time.

TPU-native redesign: the optimizer owns the optax ``GradientTransformation`` and a
*sharded* opt-state pytree (built from sharded params, so ZeRO-style optimizer
sharding is automatic — the reference's FSDP2 ``data_ptr`` re-mapping dance,
``accelerator.py:1400-1457``, has no analog).  The reference's lazy grad
all-reduce (``optimizer.py:149-155``) is unnecessary: gradients come out of the
jitted step already reduced over data axes by GSPMD.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import optax

from .state import AcceleratorState, GradientState
from .telemetry import get_telemetry as _get_telemetry
from .telemetry import span as _span

__all__ = ["AcceleratedOptimizer"]


def _update_body(
    tx_update, params, opt_state, grads, clip_norm, clip_value, health_ok=None,
    norm_ndp=None,
):
    """One optimizer update (traced body shared by the jit variants).

    ``clip_norm`` / ``clip_value`` < 0 disable the respective clip (static
    python floats would retrigger compilation; pass as arrays); 0 is a real
    clip that zeroes gradients, matching torch's ``clip_grad_{norm,value}_(0)``.
    Value clip (elementwise, reference ``clip_grad_value_``) applies first,
    then norm clip — matching a torch loop that calls both before ``step()``.

    Numerical-health gate (resilience/health.py): the PRE-clip global norm is
    the health verdict — a value clip would mask an Inf gradient into a
    finite one, so finiteness must be judged before any clip touches the
    tree.  When the verdict (optionally ANDed with ``health_ok``, the fused
    step's loss-finiteness flag) fails, the whole update is ``jnp.where``-
    gated to a zero delta: params AND optimizer state come back bit-identical
    (optax ``count`` included), all inside this one traced program — no extra
    dispatch, no host round-trip.  The returned ``health_norm`` is that
    pre-clip norm, forced non-finite whenever the verdict failed, so the host
    can detect the skip from a value it was reading anyway.

    ``norm_ndp`` (static; set by every caller on a mesh with active
    data-parallel axes, ``parallel/zero.py:zero_degree``) switches the two
    global norms to the canonical dp-chunked association and select-fences
    the update's dataflow boundaries.  Both are numerics-parity devices for
    the ZeRO sharded update: the chunked norm reduces identically over a
    replicated and a dp-sharded gradient tree, and the fences (selects on an
    always-true-at-runtime pred) stop XLA from FMA-contracting multiplies
    across stage boundaries differently in differently-partitioned programs.
    Selects pass values through bit-exactly, so on any single program this is
    a no-op numerically; across the eager / fused / fused+ZeRO programs it is
    what makes them agree to the last bit (tests/test_zero.py matrix).  With
    ``norm_ndp=None`` (no dp axes — the overwhelmingly common single-device
    test path) this body is exactly the legacy one.
    """
    if norm_ndp:
        from .parallel.zero import chunked_global_norm

        # Runtime-true, compile-time-opaque fence pred.  x == x is the
        # NaN-check: True for every real clip argument INCLUDING inf
        # (clip_grad_norm_(inf) is the standard measure-without-clipping
        # idiom and must not trip the fence), never constant-foldable for
        # floats.  ANDing health_ok keeps the poisoned-step semantics:
        # zeroed grads make the norms finite, but ``ok`` still fails via
        # health_ok and health_norm is forced NaN below.
        fence = jnp.logical_and(clip_norm == clip_norm, clip_value == clip_value)
        if health_ok is not None:
            fence = jnp.logical_and(fence, health_ok)
        grads = jax.tree_util.tree_map(
            lambda g: jnp.where(fence, g, jnp.zeros_like(g)), grads
        )
        health_norm = chunked_global_norm(grads, norm_ndp, fence)
    else:
        health_norm = optax.global_norm(grads)
    ok = jnp.isfinite(health_norm)
    if health_ok is not None:
        ok = jnp.logical_and(ok, health_ok)
        health_norm = jnp.where(health_ok, health_norm, jnp.nan)
    grads = jax.tree_util.tree_map(
        lambda g: jnp.where(clip_value >= 0, jnp.clip(g, -clip_value, clip_value), g), grads
    )
    if norm_ndp:
        gnorm = chunked_global_norm(grads, norm_ndp, fence)
    else:
        gnorm = optax.global_norm(grads)
    scale = jnp.where(
        clip_norm >= 0, jnp.minimum(1.0, clip_norm / jnp.maximum(gnorm, 1e-12)), 1.0
    )
    grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
    if norm_ndp:
        grads = jax.tree_util.tree_map(
            lambda g: jnp.where(ok, g, jnp.zeros_like(g)), grads
        )
    updates, new_opt_state = tx_update(grads, opt_state, params)
    if norm_ndp:
        updates = jax.tree_util.tree_map(
            lambda u: jnp.where(ok, u, jnp.zeros_like(u)), updates
        )
    new_params = optax.apply_updates(params, updates)
    new_params = jax.tree_util.tree_map(
        lambda n, o: jnp.where(ok, n, o), new_params, params
    )
    new_opt_state = jax.tree_util.tree_map(
        lambda n, o: jnp.where(ok, n, o), new_opt_state, opt_state
    )
    return new_params, new_opt_state, gnorm, health_norm


_update_step = partial(jax.jit, donate_argnums=(1, 2), static_argnums=(0,))(_update_body)


class AcceleratedOptimizer:
    """Wraps an optax transformation (or a converted torch optimizer) so the
    training loop keeps its imperative ``optimizer.step()`` shape.

    Gradients land here from ``accelerator.backward`` (the accumulation buffer);
    ``step()`` is a no-op while ``GradientState.sync_gradients`` is False —
    identical observable semantics to reference ``optimizer.py:145-181``.
    """

    def __init__(
        self,
        tx: optax.GradientTransformation,
        model=None,
        torch_optimizer=None,
        initial_lr: Optional[float] = None,
        host_offload_state: bool = False,
    ):
        self.tx = tx
        self._host_offload_requested = host_offload_state
        self._update_fn = None
        self.model = model  # PreparedModel owning the params
        self.torch_optimizer = torch_optimizer  # shadow for scheduler compat
        self.initial_lr = initial_lr
        self.gradient_state = GradientState()
        self.accelerator_state = AcceleratorState() if AcceleratorState._shared_state else None
        self.opt_state = None
        self._step_was_skipped = False
        # Persistent clips (<0: disabled) — set by engine-dialect config
        # (e.g. ds_config gradient_clipping) and applied every step.
        self._clip_norm = -1.0
        self._clip_value = -1.0
        # One-shot overrides armed by accelerator.clip_grad_{norm,value}_ and
        # consumed by the next real update — the reference's calls mutate
        # grads once per invocation, not forever after.
        self._clip_norm_once: Optional[float] = None
        self._clip_value_once: Optional[float] = None
        self._step_count = 0
        # Health-guard observables: the post-value-clip norm the clip logic
        # used, and the PRE-clip norm (non-finite <=> the update was gated to
        # a zero delta in-program).  Device scalars — reading them is a sync,
        # so only HealthGuard.check() (or the user) ever floats them.
        self._last_grad_norm = None
        self._last_health_norm = None
        # Checkpoint-manifest record of the carried opt-state layout; the
        # ZeRO fused step (pipeline/train_step.py) flips it to its sharded
        # descriptor when it re-places the state.
        self._opt_state_layout = {"kind": "replicated", "axes": [], "degree": 1}
        if model is not None:
            self._init_state()

    def _init_state(self):
        if self._host_offload_requested:
            # fsdp_plugin.cpu_offload / DeepSpeed offload_optimizer: optimizer
            # state lives in pinned host memory between steps and rides
            # explicit transfers inside the update program.
            from .parallel.host_offload import host_memory_kind, host_offload

            if host_memory_kind() is None:
                import warnings

                warnings.warn(
                    "cpu_offload requested but this backend exposes no host "
                    "memory space; optimizer state stays in device memory."
                )
                self._host_offload_requested = False
            else:
                self.tx = host_offload(self.tx)
        self.opt_state = self.tx.init(self.model.params)
        self._build_update_fn()

    def _norm_ndp(self) -> Optional[int]:
        """Static dp-chunking degree for the canonical global norm — set on
        any mesh with active data-parallel axes so the eager update, the
        fused step and the ZeRO fused step all reduce in the same association
        (see ``_update_body``); None on dp=1 meshes keeps the legacy path."""
        mesh = getattr(self.accelerator_state, "mesh", None)
        if mesh is None:
            return None
        from .parallel.zero import supported, zero_degree

        if not supported(mesh)[0]:
            # Model-axis meshes keep the legacy norm (ZeRO can't run there,
            # and the chunked reshape would fight fsdp/tp layouts).
            return None
        ndp = zero_degree(mesh)
        return ndp if ndp > 1 else None

    def _build_update_fn(self):
        body = partial(_update_body, self.tx.update, norm_ndp=self._norm_ndp())
        if self._host_offload_requested:
            if jax.default_backend() == "tpu":
                # The carried state must come back in host memory: pin the out
                # shardings so the donated pinned_host buffers are reused
                # instead of clashing with default device-placed outputs.
                opt_sh = jax.tree_util.tree_map(
                    lambda x: x.sharding if isinstance(x, jax.Array) else None,
                    self.opt_state,
                )
                self._update_fn = jax.jit(
                    body,
                    donate_argnums=(0, 1),
                    out_shardings=(None, opt_sh, None, None),
                )
            else:
                # CPU smoke path: the backend cannot execute D2H placement
                # inside jit (the state silently returns in device memory —
                # numerics identical); donating the pinned_host input against
                # a device-kind output would crash, so no donation here.
                self._update_fn = jax.jit(body)
        else:
            # Same donation contract as the legacy module-level _update_step
            # (params + opt state); per-optimizer so the static norm_ndp and
            # this optimizer's tx ride the closure.
            self._update_fn = jax.jit(body, donate_argnums=(0, 1))

    # -- torch-optimizer-shaped surface -------------------------------------

    @property
    def param_groups(self):
        if self.torch_optimizer is not None:
            return self.torch_optimizer.param_groups
        return [{"lr": self.learning_rate}]

    @property
    def learning_rate(self) -> Optional[float]:
        if self.opt_state is not None and hasattr(self.opt_state, "hyperparams"):
            lr = self.opt_state.hyperparams.get("learning_rate")
            return float(lr) if lr is not None else self.initial_lr
        return self.initial_lr

    def set_learning_rate(self, lr: float):
        if self.opt_state is not None and hasattr(self.opt_state, "hyperparams"):
            self.opt_state.hyperparams["learning_rate"] = jnp.asarray(lr, jnp.float32)
        # Keep the torch-visible surface consistent: user code (and the
        # reference's checkpoint-resume asserts) reads the lr back through
        # ``optimizer.param_groups[0]["lr"]``, which lives on the shadow torch
        # optimizer — a torch scheduler's load_state_dict does NOT write it.
        # ONLY when the groups share one lr: per-group schedules are advanced
        # by the torch scheduler's own step(), and overwriting distinct group
        # lrs with lr[0] would collapse them onto group 0's schedule.
        if self.torch_optimizer is not None:
            groups = self.torch_optimizer.param_groups
            if len({float(g["lr"]) for g in groups}) <= 1:
                for group in groups:
                    group["lr"] = lr

    def zero_grad(self, set_to_none: bool = True):
        """Clear accumulated gradients — only when a sync step just happened
        (reference ``optimizer.py:112``: no-op during accumulation)."""
        if self.gradient_state.sync_gradients and self.model is not None:
            self.model._clear_grads()

    def step(self, closure=None):
        if not self.gradient_state.sync_gradients:
            self._step_was_skipped = True
            return
        if self.model is None or self.model._accum_grads is None:
            self._step_was_skipped = True
            return
        with _span("optimizer.step"):
            self._apply_update()
        # A completed step is the telemetry heartbeat: step-time histogram,
        # tokens/sec + MFU gauges, HBM gauges, stall-watchdog beat.
        _get_telemetry().record_step()

    def _apply_update(self):
        _get_telemetry().count_dispatch()  # jitted optax update program
        grads = self.model._consume_grads()
        from .resilience import faultinject

        if faultinject.nan_armed():
            poison = faultinject.grad_poison_scale(self._step_count + 1)
            if poison is not None:
                grads = jax.tree_util.tree_map(lambda g: g * poison, grads)
        clip_norm = self._clip_norm if self._clip_norm_once is None else self._clip_norm_once
        clip_value = self._clip_value if self._clip_value_once is None else self._clip_value_once
        self._clip_norm_once = None
        self._clip_value_once = None
        if self._update_fn is None and self.tx is not None:
            # Rebuilt lazily after unpickle (the jitted closure doesn't pickle).
            self._build_update_fn()
        if self._update_fn is not None:
            new_params, self.opt_state, gnorm, health_norm = self._update_fn(
                self.model.params,
                self.opt_state,
                grads,
                jnp.asarray(clip_norm, jnp.float32),
                jnp.asarray(clip_value, jnp.float32),
            )
        else:
            new_params, self.opt_state, gnorm, health_norm = _update_step(
                self.tx.update,
                self.model.params,
                self.opt_state,
                grads,
                jnp.asarray(clip_norm, jnp.float32),
                jnp.asarray(clip_value, jnp.float32),
            )
        self.model._set_params(new_params)
        self._last_grad_norm = gnorm
        self._last_health_norm = health_norm
        self._step_was_skipped = False
        self._step_count += 1
        if self.torch_optimizer is not None:
            # Keep the shadow's step bookkeeping in sync: torch LR schedulers
            # warn "scheduler.step() before optimizer.step()" otherwise (the
            # optax path never calls the shadow's step()).  Current torch
            # checks _opt_called; older versions compared _step_count.
            self.torch_optimizer._opt_called = True
            self.torch_optimizer._step_count = getattr(self.torch_optimizer, "_step_count", 0) + 1

    @property
    def step_was_skipped(self) -> bool:
        """Parity: reference ``optimizer_step_was_skipped`` (``accelerator.py:3764``)."""
        return self._step_was_skipped

    # Pickling (reference tests/test_optimizer.py:26): the optax transform is
    # a closure (unpicklable) and the model holds compiled steps — both drop;
    # the transform rebuilds from the picklable shadow torch optimizer, and
    # the model re-pairs at the next prepare() (same contract as Accelerator).
    def __getstate__(self):
        state = {
            k: v
            for k, v in self.__dict__.items()
            if k not in ("tx", "model", "_update_fn")
        }
        # Jitted update (a closure over tx.update) is unpicklable; it rebuilds
        # lazily in _apply_update after the next prepare() re-pairs a model.
        state["_update_fn"] = None
        state["opt_state"] = jax.device_get(self.opt_state) if self.opt_state is not None else None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self.model = None
        if self.torch_optimizer is not None:
            from .utils.torch_bridge import convert_optimizer

            self.tx, _ = convert_optimizer(self.torch_optimizer)
        else:
            self.tx = None

    def state_dict(self) -> dict:
        return {
            "opt_state": jax.device_get(self.opt_state),
            "step_count": self._step_count,
            "initial_lr": self.initial_lr,
        }

    def load_state_dict(self, state_dict: dict):
        target = self.opt_state
        loaded = state_dict["opt_state"]
        # Restore with the live opt-state's shardings — but ONLY where the
        # live leaf is meaningfully placed (spans >1 device, or lives in a
        # non-default memory space like pinned_host).  A fresh ``tx.init``
        # leaves scalar leaves (optax's ``count``) as UNCOMMITTED
        # single-device arrays whose placement the next update's jit resolves
        # against the params; ``device_put``-committing them to the init
        # device pins them to device 0 and a resumed run on a multi-device
        # mesh then fails jit placement ("incompatible devices") on its very
        # first step.
        flat_t, treedef = jax.tree_util.tree_flatten(target)
        flat_l = jax.tree_util.tree_leaves(loaded)
        placed = []
        for t, l in zip(flat_t, flat_l):
            sharding = getattr(t, "sharding", None) if isinstance(t, jax.Array) else None
            pinned = False
            if sharding is not None and getattr(sharding, "memory_kind", None) is not None:
                try:
                    default_kind = next(iter(sharding.device_set)).default_memory().kind
                except Exception:
                    default_kind = None
                pinned = default_kind is not None and sharding.memory_kind != default_kind
            if sharding is not None and (len(sharding.device_set) > 1 or pinned):
                placed.append(jax.device_put(jnp.asarray(l), sharding))
            else:
                placed.append(l)
        self.opt_state = jax.tree_util.tree_unflatten(treedef, placed)
        self._step_count = state_dict.get("step_count", 0)

    def __repr__(self):
        return f"AcceleratedOptimizer({self.tx.__class__.__name__}, lr={self.learning_rate})"
