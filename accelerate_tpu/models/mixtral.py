"""Mixtral-style MoE decoder — llama attention + top-k routed expert FFN.

Reference analog: none in-repo (the reference marks MoE modules as DeepSpeed
ZeRO-3 leaves, ``utils/dataclasses.py:1399``, and delegates everything else);
this model exercises our net-new expert-parallel path (``ops/moe.py``) end to
end over the ``ep`` mesh axis.

Same TPU-first layout as ``models/llama.py``: stacked per-layer params scanned
with ``lax.scan``, bf16 compute / fp32 params, every weight carrying a
PartitionSpec — expert weights additionally sharded on ``ep``.
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Any, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.moe import expert_capacity, moe_ffn
from . import llama as _llama
from .llama import cross_entropy, labels_and_weights  # re-export for parity with llama

__all__ = [
    "MixtralConfig",
    "init_params",
    "apply",
    "loss_fn",
    "PARTITION_RULES",
    "param_specs",
]


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: Optional[int] = None
    max_seq_len: int = 8192
    rope_theta: float = 1000000.0
    rms_eps: float = 1e-5
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    # "dense" = Switch-style dispatch/combine einsums (the GSPMD ep-sharded
    # path; capacity_factor applies); "ragged" = exact grouped matmul via
    # lax.ragged_dot (no capacity padding, zero drops — per-device: raises
    # under an active ep>1 mesh where group sizes would be data-dependent
    # across shards).
    moe_impl: str = "dense"
    router_aux_coef: float = 0.01
    router_z_coef: float = 0.001
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    fp8: bool = False  # route attention matmuls through ops/fp8.py (expert FFN stays bf16)
    # Attention implementation knobs shared with llama (attention_block):
    # "auto"/"einsum"/"flash"/"pallas"; sp_impl picks ring vs ulysses at sp>1.
    attention_impl: str = "auto"
    sp_impl: str = "ring"
    # "chunked" streams the LM-head loss over vocab tiles (ops/chunked_ce.py)
    # — no [B, S, V] logits tensor; same knob as LlamaConfig.loss_impl.
    # int8 KV cache for generation (shared machinery; see LlamaConfig).
    kv_cache_quant: bool = False
    loss_impl: str = "dense"
    loss_chunk_size: int = 4096

    def __post_init__(self):
        if self.attention_impl not in ("auto", "einsum", "flash", "pallas"):
            raise ValueError(
                "attention_impl must be 'auto', 'einsum', 'flash' or 'pallas', "
                f"got {self.attention_impl!r}"
            )
        if self.sp_impl not in ("ring", "ulysses"):
            raise ValueError(f"sp_impl must be 'ring' or 'ulysses', got {self.sp_impl!r}")
        if self.loss_impl not in ("dense", "chunked"):
            raise ValueError(f"loss_impl must be 'dense' or 'chunked', got {self.loss_impl!r}")
        if self.moe_impl not in ("dense", "ragged"):
            raise ValueError(f"moe_impl must be 'dense' or 'ragged', got {self.moe_impl!r}")

    @property
    def head_dim_(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @classmethod
    def tiny(cls, **kw) -> "MixtralConfig":
        defaults = dict(
            vocab_size=256,
            hidden_size=64,
            intermediate_size=96,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            max_seq_len=128,
            num_experts=4,
            top_k=2,
            remat=False,
        )
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def mixtral_8x7b(cls, **kw) -> "MixtralConfig":
        defaults = dict(
            vocab_size=32000,
            hidden_size=4096,
            intermediate_size=14336,
            num_layers=32,
            num_heads=32,
            num_kv_heads=8,
            num_experts=8,
            top_k=2,
        )
        defaults.update(kw)
        return cls(**defaults)

    def num_params(self) -> int:
        d, f, v, l = self.hidden_size, self.intermediate_size, self.vocab_size, self.num_layers
        hd = self.head_dim_
        attn = d * self.num_heads * hd + 2 * d * self.num_kv_heads * hd + self.num_heads * hd * d
        moe = self.num_experts * 3 * d * f + d * self.num_experts
        norms = 2 * d
        return l * (attn + moe + norms) + 2 * v * d + d

    def flops_per_token(self) -> float:
        """Active-path FLOPs per token: only top_k experts run per token."""
        d, f, l = self.hidden_size, self.intermediate_size, self.num_layers
        hd = self.head_dim_
        attn = d * self.num_heads * hd + 2 * d * self.num_kv_heads * hd + self.num_heads * hd * d
        moe_active = self.top_k * 3 * d * f + d * self.num_experts
        return 6.0 * (l * (attn + moe_active) + 2 * self.vocab_size * d)


# Expert weights add the ``ep`` axis ahead of the usual fsdp/tp matmul layout.
PARTITION_RULES: list[tuple[str, P]] = [
    (r"embed", P("tp", "fsdp")),
    (r"layers/wq", P(None, "fsdp", "tp")),
    (r"layers/wk", P(None, "fsdp", "tp")),
    (r"layers/wv", P(None, "fsdp", "tp")),
    (r"layers/wo", P(None, "tp", "fsdp")),
    (r"layers/router", P(None, None, None)),
    (r"layers/w_gate", P(None, "ep", "fsdp", "tp")),
    (r"layers/w_up", P(None, "ep", "fsdp", "tp")),
    (r"layers/w_down", P(None, "ep", "tp", "fsdp")),
    (r"layers/ln_", P(None, None)),
    (r"final_norm", P(None)),
    (r"lm_head", P("fsdp", "tp")),
]


def _param_shapes(c: MixtralConfig) -> dict:
    d, f, hd, L, E = c.hidden_size, c.intermediate_size, c.head_dim_, c.num_layers, c.num_experts
    return {
        "embed": (c.vocab_size, d),
        "layers": {
            "wq": (L, d, c.num_heads * hd),
            "wk": (L, d, c.num_kv_heads * hd),
            "wv": (L, d, c.num_kv_heads * hd),
            "wo": (L, c.num_heads * hd, d),
            "router": (L, d, E),
            "w_gate": (L, E, d, f),
            "w_up": (L, E, d, f),
            "w_down": (L, E, f, d),
            "ln_attn": (L, d),
            "ln_mlp": (L, d),
        },
        "final_norm": (d,),
        "lm_head": (d, c.vocab_size),
    }


def param_specs(config: MixtralConfig) -> dict:
    from ..parallel.sharding import spec_from_rules

    shapes = _param_shapes(config)

    def one(kp, shape):
        path = "/".join(str(getattr(k, "key", k)) for k in kp)
        spec = spec_from_rules(path, len(shape), PARTITION_RULES)
        return spec if spec is not None else P(*([None] * len(shape)))

    return jax.tree_util.tree_map_with_path(one, shapes, is_leaf=lambda x: isinstance(x, tuple))


def init_params(config: MixtralConfig, key: jax.Array) -> dict:
    shapes = _param_shapes(config)
    leaves, treedef = jax.tree_util.tree_flatten(shapes, is_leaf=lambda x: isinstance(x, tuple))
    keys = jax.tree_util.tree_unflatten(treedef, list(jax.random.split(key, len(leaves))))

    def init_one(kp, shape, k):
        # Name-based dispatch (see llama.init_params): shape tests misfire
        # when e.g. vocab_size == num_layers.
        name = str(getattr(kp[-1], "key", kp[-1]))
        if name in ("ln_attn", "ln_mlp", "final_norm"):
            return jnp.ones(shape, config.param_dtype)  # norm scales
        fan_in = config.hidden_size if name == "embed" else shape[-2]
        scale = 1.0 / np.sqrt(fan_in)
        return (jax.random.truncated_normal(k, -2.0, 2.0, shape, jnp.float32) * scale).astype(
            config.param_dtype
        )

    return jax.tree_util.tree_map_with_path(
        init_one, shapes, keys, is_leaf=lambda x: isinstance(x, tuple)
    )


def _ep_active() -> bool:
    from ..parallel.sharding import _abstract_mesh

    m = _abstract_mesh()
    return bool(m is not None and not m.empty and "ep" in m.axis_names and m.shape["ep"] > 1)


def _sharded_batch_axes() -> tuple:
    """Data-consuming mesh axes with size > 1 on the ambient mesh (the axes
    the batch dimension is sharded over)."""
    from ..parallel.sharding import _abstract_mesh

    m = _abstract_mesh()
    if m is None or m.empty:
        return ()
    return tuple(
        a for a in ("dcn_dp", "dp", "fsdp") if a in m.axis_names and m.shape[a] > 1
    )


def _check_moe_impl(c: MixtralConfig) -> None:
    """Fail fast (before any computation touches the mesh) when the ragged
    impl meets an expert-parallel mesh, and warn when it meets a sharded
    batch: the global-token argsort/bincount in the ragged path gathers the
    FULL token set onto every device, silently discarding the data
    parallelism the mesh was built for."""
    if c.moe_impl != "ragged":
        return
    if _ep_active():
        raise ValueError(
            "moe_impl='ragged' cannot run under an ep>1 mesh: ragged "
            "group sizes are data-dependent per shard.  Use "
            "moe_impl='dense' for expert-parallel meshes."
        )
    batch_axes = _sharded_batch_axes()
    if batch_axes:
        warnings.warn(
            f"moe_impl='ragged' under a mesh with sharded batch axes "
            f"{batch_axes}: the ragged grouped-matmul sorts and bins the "
            "GLOBAL token set, so XLA all-gathers the full batch onto every "
            "device before routing — the per-device work does not shrink "
            "with the mesh.  Use moe_impl='dense' for dp/fsdp meshes (its "
            "dispatch einsum partitions over the batch axes)."
        )


def _moe(h, p, c: MixtralConfig, capacity):
    """Dispatch on ``moe_impl``: Switch dense dispatch (GSPMD ep path) or the
    exact ragged grouped matmul (per-device; see MixtralConfig)."""
    if c.moe_impl == "ragged":
        _check_moe_impl(c)
        from ..ops.moe import moe_ffn_ragged

        return moe_ffn_ragged(
            h, p["router"], p["w_gate"], p["w_up"], p["w_down"],
            top_k=c.top_k, compute_dtype=c.dtype,
        )
    return moe_ffn(
        h, p["router"], p["w_gate"], p["w_up"], p["w_down"],
        top_k=c.top_k, capacity=capacity, compute_dtype=c.dtype,
    )


def _layer(
    carry, layer_params, *, config: MixtralConfig, mask, positions, act_spec, capacity,
    kv_valid=None,
):
    x, aux_acc = carry
    c = config
    p = layer_params
    x = _llama.attention_block(x, p, c, mask, positions, kv_valid=kv_valid)

    h = _llama._rms_norm(x, p["ln_mlp"], c.rms_eps)
    y, aux = _moe(h, p, c, capacity)
    x = x + y
    if act_spec is not None:
        x = _llama._maybe_constrain(x, act_spec)
    aux_acc = {
        "load_balancing_loss": aux_acc["load_balancing_loss"] + aux["load_balancing_loss"],
        "router_z_loss": aux_acc["router_z_loss"] + aux["router_z_loss"],
        "fraction_dropped": aux_acc["fraction_dropped"] + aux["fraction_dropped"],
    }
    return (x, aux_acc), None


def lm_head(params: dict, config: MixtralConfig) -> jax.Array:
    """The [d, V] head in compute dtype — single source for apply() and the
    chunked loss (mirrors llama.lm_head)."""
    return params["lm_head"].astype(config.dtype)


def apply(
    params: dict,
    input_ids: jax.Array,
    config: MixtralConfig,
    positions: Optional[jax.Array] = None,
    attention_mask: Optional[jax.Array] = None,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Forward pass: token ids [B, S] -> (logits [B, S, V] fp32, mean aux losses)."""
    hidden, aux = apply_hidden(params, input_ids, config, positions, attention_mask)
    logits = (hidden @ lm_head(params, config)).astype(jnp.float32)
    return logits, aux


def apply_hidden(
    params: dict,
    input_ids: jax.Array,
    config: MixtralConfig,
    positions: Optional[jax.Array] = None,
    attention_mask: Optional[jax.Array] = None,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Trunk forward -> (final-normed hidden [B, S, d], mean aux losses) —
    the chunked loss consumes the hidden directly (no logits tensor)."""
    _check_moe_impl(config)
    c = config
    b, s = input_ids.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s), (b, s))
    # Padding stays factored as a [B, S] key-validity vector (see llama.apply):
    # attention_block picks flash/ring/ulysses without an [S, S] mask.
    kv_valid = attention_mask.astype(bool) if attention_mask is not None else None

    x = _llama._embed_lookup(params["embed"], input_ids, c.dtype)
    act_spec = P(("dcn_dp", "dp", "fsdp"), "sp", None)
    x = _llama._maybe_constrain(x, act_spec)
    capacity = expert_capacity(s, c.num_experts, c.top_k, c.capacity_factor)

    aux0 = {
        "load_balancing_loss": jnp.zeros((), jnp.float32),
        "router_z_loss": jnp.zeros((), jnp.float32),
        "fraction_dropped": jnp.zeros((), jnp.float32),
    }

    def body(carry, lp):
        return _layer(
            carry, _llama._dequant_layer(lp), config=c, mask=None,
            positions=positions, act_spec=act_spec,
            capacity=capacity, kv_valid=kv_valid,
        )

    if c.remat:
        body = jax.checkpoint(body, policy=jax.checkpoint_policies.nothing_saveable)
    (x, aux), _ = jax.lax.scan(body, (x, aux0), params["layers"])
    aux = {k: v / c.num_layers for k, v in aux.items()}
    return _llama._rms_norm(x, params["final_norm"], c.rms_eps), aux


def loss_fn(params: dict, batch: dict, config: MixtralConfig) -> jax.Array:
    """Next-token cross-entropy + router aux losses (Switch/ST-MoE recipe).

    ``config.loss_impl == "chunked"`` streams the head matmul over vocab
    tiles (``ops/chunked_ce.py``) — no [B, S, V] logits tensor."""
    labels, weights = labels_and_weights(batch)
    if config.loss_impl == "chunked":
        from ..ops.chunked_ce import chunked_cross_entropy

        hidden, aux = apply_hidden(
            params, batch["input_ids"], config, attention_mask=batch.get("attention_mask")
        )
        ce = chunked_cross_entropy(
            hidden, lm_head(params, config), labels, weights, config.loss_chunk_size
        )
    else:
        logits, aux = apply(
            params, batch["input_ids"], config, attention_mask=batch.get("attention_mask")
        )
        ce = cross_entropy(logits, labels, weights)
    return (
        ce
        + config.router_aux_coef * aux["load_balancing_loss"]
        + config.router_z_coef * aux["router_z_loss"]
    )


# ---------------------------------------------------------------------------
# KV-cache inference (shared driver: models/generation.py)
# ---------------------------------------------------------------------------


def quantize_weights(params: dict, block_size: int = 64) -> dict:
    """int8-weight-resident storage for the stacked MoE blocks — the expert
    tensors ([L, E, d, f]) are the dominant bytes, making this the classic
    MoE memory win.  The router stays full precision (its logits pick the
    top-k experts; a near-tie flip from quantization error would change
    outputs for ~1/f of the byte win), as do embed/lm_head/norms.  See
    ``llama.quantize_weights``."""
    from ..utils.quantization import quantize_layer_stack

    out = dict(params)
    out["layers"] = quantize_layer_stack(params["layers"], block_size, skip=("router",))
    return out


def init_cache(config: MixtralConfig, batch_size: int, max_len: int) -> dict:
    """Zeroed KV cache (same layout as llama: attention is shared code)."""
    from .generation import make_kv_cache

    c = config
    return make_kv_cache(
        c.num_layers, batch_size, max_len, c.num_kv_heads, c.head_dim_, c.dtype,
        quantized=getattr(c, "kv_cache_quant", False),
    )


def apply_cached(
    params: dict,
    input_ids: jax.Array,
    config: MixtralConfig,
    cache: dict,
) -> tuple[jax.Array, dict]:
    """Forward over new tokens with cache read/write; router aux losses are
    not accumulated (inference)."""
    _check_moe_impl(config)
    from .generation import check_cache_room

    c = config
    b, s = input_ids.shape
    index = cache["index"]
    check_cache_room(index, s, cache["k"].shape[2])
    positions = jnp.broadcast_to(index + jnp.arange(s), (b, s))
    x = _llama._embed_lookup(params["embed"], input_ids, c.dtype)
    capacity = expert_capacity(s, c.num_experts, c.top_k, c.capacity_factor)

    def body(carry, xs):
        lp, ck, cv = xs
        lp = _llama._dequant_layer(lp)
        y, ck, cv = _llama._attention_block_cached(carry, lp, c, ck, cv, index, positions)
        h = _llama._rms_norm(y, lp["ln_mlp"], c.rms_eps)
        ffn, _ = _moe(h, lp, c, capacity)
        return y + ffn, (ck, cv)

    from .generation import pack_cache_for_scan, unpack_cache_from_scan

    ck_in, cv_in, quant = pack_cache_for_scan(cache)
    x, (new_k, new_v) = jax.lax.scan(body, x, (params["layers"], ck_in, cv_in))
    x = _llama._rms_norm(x, params["final_norm"], c.rms_eps)
    logits = (x @ params["lm_head"].astype(c.dtype)).astype(jnp.float32)
    return logits, unpack_cache_from_scan(new_k, new_v, index + s, quant)


def generate(
    params: dict,
    input_ids: jax.Array,
    config: MixtralConfig,
    max_new_tokens: int,
    temperature: float = 0.0,
    key=None,
    max_len=None,
    top_k: int = 0,
    top_p: float = 1.0,
    prefill_chunk=None,
) -> jax.Array:
    """Autoregressive generation (one compiled XLA program; see
    models/generation.py)."""
    from .generation import generate_loop

    return generate_loop(
        apply_cached, init_cache, params, input_ids, config,
        max_new_tokens, temperature=temperature, key=key, max_len=max_len,
        top_k=top_k, top_p=top_p, prefill_chunk=prefill_chunk,
    )


def speculative_generate(
    params: dict,
    draft_params: dict,
    input_ids: jax.Array,
    config: MixtralConfig,
    draft_config,
    max_new_tokens: int,
    num_draft_tokens: int = 4,
    max_len=None,
    return_stats: bool = False,
    temperature: float = 0.0,
    key=None,
) -> jax.Array:
    """Speculative decoding (see ``models/generation.py``): greedy by
    default, distribution-exact sampling with ``temperature>0`` + ``key``.
    The draft can be any family module with the same vocab — a dense llama
    drafting for a Mixtral target is the classic cheap-draft pairing —
    pass that family's ``apply_cached``/``init_cache`` via
    ``speculative_generate_loop`` directly; this wrapper uses a (smaller)
    Mixtral draft.  Batch 1 only."""
    from .generation import speculative_generate_loop

    return speculative_generate_loop(
        apply_cached, init_cache, params, config,
        apply_cached, init_cache, draft_params, draft_config,
        input_ids, max_new_tokens,
        num_draft_tokens=num_draft_tokens, max_len=max_len,
        return_stats=return_stats, temperature=temperature, key=key,
    )


def generate_beam(
    params: dict,
    input_ids: jax.Array,
    config: MixtralConfig,
    max_new_tokens: int,
    num_beams: int = 4,
    length_penalty: float = 1.0,
    eos_token_id=None,
    max_len=None,
) -> jax.Array:
    """Beam-search generation (see ``models/generation.py beam_search``)."""
    from .generation import beam_search

    return beam_search(
        apply_cached, init_cache, params, input_ids, config, max_new_tokens,
        num_beams=num_beams, length_penalty=length_penalty,
        eos_token_id=eos_token_id, max_len=max_len,
    )
