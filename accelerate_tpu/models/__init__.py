from . import llama
