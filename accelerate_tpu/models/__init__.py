from . import bert, gpt2, llama, mixtral, t5, vit
