from . import llama, mixtral
