from . import bert, gpt2, llama, mixtral, resnet, t5, vit
