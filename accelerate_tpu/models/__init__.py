from . import bert, gpt2, hf_export, hf_import, llama, mixtral, resnet, t5, vit
