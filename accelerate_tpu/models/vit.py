"""ViT — native vision-encoder model family.

Parity rationale: the reference's CV story runs torchvision/timm models
through its model-agnostic loop (``examples/cv_example.py``,
``examples/complete_cv_example.py``); its own test fixtures are
regression MLPs.  This family covers the vision-encoder architecture
class natively so image training does not require the torch bridge:
patchify-as-matmul embedding (a strided conv is exactly a reshape +
``[p*p*C, d]`` matmul — one MXU-shaped contraction, no conv lowering),
pre-LN transformer blocks, learned position embeddings, CLS-token or
mean pooling, classification head.

Same TPU-first layout as the other families: stacked per-layer params
under ``lax.scan``, bf16 compute / fp32 params, partition rules over the
named mesh, optional per-block remat.  Sequence parallelism composes via
the shared ``sp_attention`` dispatch (bidirectional, like BERT) with
``pool="mean"`` — the CLS token would make the token count ``N + 1``,
indivisible by the ``sp`` axis, so ``pool="cls"`` raises under an active
sp mesh instead of silently falling back.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.sharding import constrain as _constrain
from .llama import _sp_active
from .llama import sp_attention as _sp_attention
from .gpt2 import _layer_norm

__all__ = [
    "ViTConfig",
    "init_params",
    "apply",
    "classification_loss_fn",
    "PARTITION_RULES",
    "param_specs",
]


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_channels: int = 3
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_ratio: int = 4
    num_labels: int = 1000
    pool: str = "cls"  # "cls" | "mean" ("mean" required under an sp mesh)
    layer_norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False
    sp_impl: str = "ring"

    def __post_init__(self):
        if self.image_size % self.patch_size:
            raise ValueError(
                f"image_size {self.image_size} must be divisible by patch_size {self.patch_size}"
            )
        if self.hidden_size % self.num_heads:
            raise ValueError("hidden_size must be divisible by num_heads")
        if self.pool not in ("cls", "mean"):
            raise ValueError(f"pool must be 'cls' or 'mean', got {self.pool!r}")
        if self.sp_impl not in ("ring", "ulysses"):
            raise ValueError(f"sp_impl must be 'ring' or 'ulysses', got {self.sp_impl!r}")

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def seq_len(self) -> int:
        return self.num_patches + (1 if self.pool == "cls" else 0)

    def num_params(self) -> int:
        leaves = jax.tree_util.tree_leaves(
            _param_shapes(self), is_leaf=lambda x: isinstance(x, tuple)
        )
        return sum(int(np.prod(s)) for s in leaves)

    @classmethod
    def tiny(cls, **kw) -> "ViTConfig":
        defaults = dict(
            image_size=32, patch_size=8, hidden_size=64, num_layers=2,
            num_heads=4, num_labels=10,
        )
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def vit_base_16(cls, **kw) -> "ViTConfig":
        return cls(**kw)  # the defaults are ViT-B/16

    @classmethod
    def vit_large_16(cls, **kw) -> "ViTConfig":
        defaults = dict(hidden_size=1024, num_layers=24, num_heads=16)
        defaults.update(kw)
        return cls(**defaults)


PARTITION_RULES: list[tuple[str, P]] = [
    (r"embeddings/patch_w", P(None, "fsdp")),
    (r"embeddings/position", P(None, "fsdp")),
    (r"layers/w_qkv", P(None, "fsdp", "tp")),
    (r"layers/w_proj", P(None, "tp", "fsdp")),
    (r"layers/w_up", P(None, "fsdp", "tp")),
    (r"layers/w_down", P(None, "tp", "fsdp")),
    (r"classifier/w", P("tp", None)),
]


def _param_shapes(c: ViTConfig) -> dict:
    d, L, m = c.hidden_size, c.num_layers, c.mlp_ratio
    emb = {
        "patch_w": (c.patch_size * c.patch_size * c.num_channels, d),
        "patch_b": (d,),
        "position": (c.seq_len, d),
    }
    if c.pool == "cls":
        emb["cls"] = (1, 1, d)
    return {
        "embeddings": emb,
        "layers": {
            "w_qkv": (L, d, 3 * d),
            "b_qkv": (L, 3 * d),
            "w_proj": (L, d, d),
            "b_proj": (L, d),
            "w_up": (L, d, m * d),
            "b_up": (L, m * d),
            "w_down": (L, m * d, d),
            "b_down": (L, d),
            "ln_attn_scale": (L, d),
            "ln_attn_bias": (L, d),
            "ln_mlp_scale": (L, d),
            "ln_mlp_bias": (L, d),
        },
        "final_ln": {"scale": (d,), "bias": (d,)},
        "classifier": {"w": (d, c.num_labels), "b": (c.num_labels,)},
    }


def param_specs(config: ViTConfig) -> dict:
    from ..parallel.sharding import spec_from_rules

    shapes = _param_shapes(config)

    def one(kp, shape):
        path = "/".join(str(getattr(k, "key", k)) for k in kp)
        spec = spec_from_rules(path, len(shape), PARTITION_RULES)
        return spec if spec is not None else P(*([None] * len(shape)))

    return jax.tree_util.tree_map_with_path(one, shapes, is_leaf=lambda x: isinstance(x, tuple))


def init_params(config: ViTConfig, key: jax.Array) -> dict:
    shapes = _param_shapes(config)
    leaves, treedef = jax.tree_util.tree_flatten(shapes, is_leaf=lambda x: isinstance(x, tuple))
    keys = jax.random.split(key, len(leaves))
    keys = jax.tree_util.tree_unflatten(treedef, list(keys))

    def init_one(kp, shape, k):
        # Dispatch on the param NAME, not shape (a shape test would zero the
        # (seq_len, d) position embedding whenever seq_len == num_layers):
        # biases, LN params and the CLS token start at zero; LN scales at one;
        # position embeddings and weight matrices normal(0.02) as in ViT.
        name = str(getattr(kp[-1], "key", kp[-1]))
        if name.endswith("_scale") or name == "scale":
            return jnp.ones(shape, config.param_dtype)
        if name.startswith("b_") or name.endswith("_bias") or name in ("bias", "b", "patch_b", "cls"):
            return jnp.zeros(shape, config.param_dtype)
        return (jax.random.normal(k, shape, jnp.float32) * 0.02).astype(config.param_dtype)

    return jax.tree_util.tree_map_with_path(
        init_one, shapes, keys, is_leaf=lambda x: isinstance(x, tuple)
    )


def _patchify(pixels: jax.Array, c: ViTConfig) -> jax.Array:
    """[B, H, W, C] -> [B, N, p*p*C]; the strided-conv patch embedding as a
    reshape + matmul (the matmul lives in ``apply``)."""
    b, hgt, wid, ch = pixels.shape
    p = c.patch_size
    x = pixels.reshape(b, hgt // p, p, wid // p, p, ch)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, (hgt // p) * (wid // p), p * p * ch)


def _layer(carry, p, *, c: ViTConfig, act_spec):
    x = carry
    d, h, hd = c.hidden_size, c.num_heads, c.head_dim
    b, s, _ = x.shape

    # Pre-LN attention sub-block.
    n = _layer_norm(x, p["ln_attn_scale"], p["ln_attn_bias"], c.layer_norm_eps)
    qkv = n @ p["w_qkv"].astype(c.dtype) + p["b_qkv"].astype(c.dtype)
    q, k, v = (t[:, :, 0] for t in jnp.split(qkv.reshape(b, s, 3, h, hd), 3, axis=2))
    if _sp_active():
        attn = _sp_attention(q, k, v, c, causal=False).reshape(b, s, d)
    else:
        scores = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32) / np.sqrt(hd)
        probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
        attn = jnp.einsum("bhst,bthd->bshd", probs, v).reshape(b, s, d)
    x = x + attn @ p["w_proj"].astype(c.dtype) + p["b_proj"].astype(c.dtype)

    # Pre-LN MLP sub-block.
    n = _layer_norm(x, p["ln_mlp_scale"], p["ln_mlp_bias"], c.layer_norm_eps)
    u = jax.nn.gelu(n @ p["w_up"].astype(c.dtype) + p["b_up"].astype(c.dtype))
    x = x + u @ p["w_down"].astype(c.dtype) + p["b_down"].astype(c.dtype)
    if act_spec is not None:
        x = _constrain(x, act_spec)
    return x, None


def apply(params: dict, pixels: jax.Array, config: ViTConfig) -> tuple[jax.Array, jax.Array]:
    """Returns (token features [B, S, d] in compute dtype, pooled [B, d] fp32).

    ``pixels`` is channels-last ``[B, H, W, C]`` (NHWC is the TPU-native
    layout; transpose NCHW inputs before calling).
    """
    c = config
    if _sp_active() and c.pool == "cls":
        raise ValueError(
            "ViT with pool='cls' cannot run sequence-parallel: the CLS token "
            "makes the token count num_patches+1, indivisible by the sp axis. "
            "Use ViTConfig(pool='mean')."
        )
    e = params["embeddings"]
    x = _patchify(pixels.astype(c.dtype), c) @ e["patch_w"].astype(c.dtype)
    x = x + e["patch_b"].astype(c.dtype)
    if c.pool == "cls":
        cls = jnp.broadcast_to(e["cls"].astype(c.dtype), (x.shape[0], 1, c.hidden_size))
        x = jnp.concatenate([cls, x], axis=1)
    x = x + e["position"].astype(c.dtype)[None]
    act_spec = P(("dcn_dp", "dp", "fsdp"), "sp", None)
    x = _constrain(x, act_spec)

    def body(carry, lp):
        return _layer(carry, lp, c=c, act_spec=act_spec)

    if c.remat:
        body = jax.checkpoint(body, policy=jax.checkpoint_policies.nothing_saveable)
    x, _ = jax.lax.scan(body, x, params["layers"])
    x = _layer_norm(x, params["final_ln"]["scale"], params["final_ln"]["bias"], c.layer_norm_eps)
    xf = x.astype(jnp.float32)
    pooled = xf[:, 0] if c.pool == "cls" else xf.mean(axis=1)
    return x, pooled


def classification_loss_fn(params: dict, batch: dict, config: ViTConfig) -> jax.Array:
    """Image-classification cross-entropy over ``batch["pixel_values"]``
    [B, H, W, C] and ``batch["labels"]`` [B]."""
    _, pooled = apply(params, batch["pixel_values"], config)
    logits = pooled @ params["classifier"]["w"].astype(jnp.float32) + params["classifier"]["b"]
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, batch["labels"][:, None], axis=-1))
