"""BERT-style bidirectional encoder — classification/MLM model family.

Parity rationale: the reference's perf/metric oracles train BERT-MRPC
(``test_utils/scripts/external_deps/test_performance.py``; Megatron
``BertTrainStep`` ``utils/megatron_lm.py:445``).  This native family covers the
encoder architecture class: bidirectional attention, learned position + token
type embeddings, LayerNorm(+bias), pooler + classification head.

Same TPU-first layout as the other families: stacked per-layer params under
``lax.scan``, bf16 compute / fp32 params, partition rules over the mesh.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.sharding import constrain as _constrain, embed_lookup as _embed_lookup
from .llama import _sp_active
from .llama import sp_attention as _sp_attention
from .gpt2 import _layer_norm

__all__ = [
    "BertConfig",
    "init_params",
    "apply",
    "classification_loss_fn",
    "PARTITION_RULES",
    "param_specs",
]


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 512
    type_vocab_size: int = 2
    num_labels: int = 2
    layer_norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False
    # Sequence parallelism backend with an sp>1 mesh axis (bidirectional
    # ring / ulysses; same knob as LlamaConfig.sp_impl).
    sp_impl: str = "ring"

    def __post_init__(self):
        if self.sp_impl not in ("ring", "ulysses"):
            raise ValueError(f"sp_impl must be 'ring' or 'ulysses', got {self.sp_impl!r}")

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def tiny(cls, **kw) -> "BertConfig":
        defaults = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4, max_seq_len=64)
        defaults.update(kw)
        return cls(**defaults)


PARTITION_RULES: list[tuple[str, P]] = [
    (r"embeddings/", P(None, "fsdp")),
    (r"layers/w_qkv", P(None, "fsdp", "tp")),
    (r"layers/w_proj", P(None, "tp", "fsdp")),
    (r"layers/w_up", P(None, "fsdp", "tp")),
    (r"layers/w_down", P(None, "tp", "fsdp")),
    (r"pooler/w", P("fsdp", "tp")),
    (r"classifier/w", P("tp", None)),
]


def _param_shapes(c: BertConfig) -> dict:
    d, L = c.hidden_size, c.num_layers
    return {
        "embeddings": {
            "word": (c.vocab_size, d),
            "position": (c.max_seq_len, d),
            "token_type": (c.type_vocab_size, d),
            "ln_scale": (d,),
            "ln_bias": (d,),
        },
        "layers": {
            "w_qkv": (L, d, 3 * d),
            "b_qkv": (L, 3 * d),
            "w_proj": (L, d, d),
            "b_proj": (L, d),
            "w_up": (L, d, 4 * d),
            "b_up": (L, 4 * d),
            "w_down": (L, 4 * d, d),
            "b_down": (L, d),
            "ln_attn_scale": (L, d),
            "ln_attn_bias": (L, d),
            "ln_mlp_scale": (L, d),
            "ln_mlp_bias": (L, d),
        },
        "pooler": {"w": (d, d), "b": (d,)},
        "classifier": {"w": (d, c.num_labels), "b": (c.num_labels,)},
    }


def param_specs(config: BertConfig) -> dict:
    from ..parallel.sharding import spec_from_rules

    shapes = _param_shapes(config)

    def one(kp, shape):
        path = "/".join(str(getattr(k, "key", k)) for k in kp)
        spec = spec_from_rules(path, len(shape), PARTITION_RULES)
        return spec if spec is not None else P(*([None] * len(shape)))

    return jax.tree_util.tree_map_with_path(one, shapes, is_leaf=lambda x: isinstance(x, tuple))


def init_params(config: BertConfig, key: jax.Array) -> dict:
    shapes = _param_shapes(config)
    leaves, treedef = jax.tree_util.tree_flatten(shapes, is_leaf=lambda x: isinstance(x, tuple))
    keys = jax.tree_util.tree_unflatten(treedef, list(jax.random.split(key, len(leaves))))

    def init_one(kp, shape, k):
        # Name-based dispatch (see llama.init_params): the old shape test
        # zeroed the (type_vocab_size, d) token-type table whenever
        # type_vocab_size == num_layers — true for the 2-layer tiny config.
        name = str(getattr(kp[-1], "key", kp[-1]))
        if name.endswith("scale"):
            return jnp.ones(shape, config.param_dtype)
        if name.startswith("b_") or name.endswith("bias") or name == "b":
            return jnp.zeros(shape, config.param_dtype)
        return (jax.random.normal(k, shape, jnp.float32) * 0.02).astype(config.param_dtype)

    return jax.tree_util.tree_map_with_path(
        init_one, shapes, keys, is_leaf=lambda x: isinstance(x, tuple)
    )


def _layer(carry, p, *, c: BertConfig, mask, kv_valid=None, act_spec):
    x = carry
    d, h, hd = c.hidden_size, c.num_heads, c.head_dim
    b, s, _ = x.shape

    qkv = x @ p["w_qkv"].astype(c.dtype) + p["b_qkv"].astype(c.dtype)
    q, k, v = (t[:, :, 0] for t in jnp.split(qkv.reshape(b, s, 3, h, hd), 3, axis=2))
    if _sp_active():
        # Sequence-parallel path: the shared dispatch (bidirectional ring /
        # ulysses + pallas fast paths); kv_valid masks KEYS only, so padded
        # QUERY rows attend normally over the valid keys — they differ from
        # the dense path (which masks query rows too) but nothing downstream
        # reads them (pooler uses [CLS]; losses weight pads to zero).
        attn = _sp_attention(q, k, v, c, causal=False, kv_valid=kv_valid).reshape(b, s, d)
    else:
        scores = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32) / np.sqrt(hd)
        scores = jnp.where(mask[:, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
        attn = jnp.einsum("bhst,bthd->bshd", probs, v).reshape(b, s, d)
    # Post-LN (original BERT): residual then LayerNorm.
    x = _layer_norm(
        x + attn @ p["w_proj"].astype(c.dtype) + p["b_proj"].astype(c.dtype),
        p["ln_attn_scale"], p["ln_attn_bias"], c.layer_norm_eps,
    )
    u = jax.nn.gelu(x @ p["w_up"].astype(c.dtype) + p["b_up"].astype(c.dtype))
    x = _layer_norm(
        x + u @ p["w_down"].astype(c.dtype) + p["b_down"].astype(c.dtype),
        p["ln_mlp_scale"], p["ln_mlp_bias"], c.layer_norm_eps,
    )
    if act_spec is not None:
        x = _constrain(x, act_spec)
    return x, None


def apply(
    params: dict,
    input_ids: jax.Array,
    config: BertConfig,
    attention_mask: Optional[jax.Array] = None,
    token_type_ids: Optional[jax.Array] = None,
) -> tuple[jax.Array, jax.Array]:
    """Returns (sequence_output [B, S, d] in compute dtype, pooled [B, d] fp32)."""
    c = config
    b, s = input_ids.shape
    kv_valid = attention_mask.astype(bool) if attention_mask is not None else None
    if _sp_active():
        mask = None  # the sp path masks per block; no [S, S] tensor
    elif kv_valid is None:
        mask = jnp.ones((b, s, s), bool)
    else:
        mask = kv_valid[:, None, :] & kv_valid[:, :, None]
    if token_type_ids is None:
        token_type_ids = jnp.zeros_like(input_ids)

    e = params["embeddings"]
    x = (
        _embed_lookup(e["word"], input_ids, c.dtype)
        + e["position"].astype(c.dtype)[:s][None]
        + e["token_type"].astype(c.dtype)[token_type_ids]
    )
    x = _layer_norm(x, e["ln_scale"], e["ln_bias"], c.layer_norm_eps)
    act_spec = P(("dcn_dp", "dp", "fsdp"), "sp", None)
    x = _constrain(x, act_spec)

    def body(carry, lp):
        return _layer(carry, lp, c=c, mask=mask, kv_valid=kv_valid, act_spec=act_spec)

    if c.remat:
        body = jax.checkpoint(body, policy=jax.checkpoint_policies.nothing_saveable)
    x, _ = jax.lax.scan(body, x, params["layers"])
    pooled = jnp.tanh(
        x[:, 0].astype(jnp.float32) @ params["pooler"]["w"].astype(jnp.float32)
        + params["pooler"]["b"]
    )
    return x, pooled


def classification_loss_fn(params: dict, batch: dict, config: BertConfig) -> jax.Array:
    """Sequence-classification cross-entropy (the BERT-MRPC oracle shape)."""
    _, pooled = apply(
        params,
        batch["input_ids"],
        config,
        attention_mask=batch.get("attention_mask"),
        token_type_ids=batch.get("token_type_ids"),
    )
    logits = pooled @ params["classifier"]["w"].astype(jnp.float32) + params["classifier"]["b"]
    labels = batch["labels"]
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))
