"""GPT-2-style decoder — second dense model family, TPU-first.

Parity rationale: the reference's Megatron bridge ships per-family train-step
handlers (``GPTTrainStep`` ``utils/megatron_lm.py:587``); our native analog is
a model family per architecture.  GPT-2 differs from llama everywhere it
matters for coverage: learned absolute positions (no RoPE), LayerNorm with
bias (not RMSNorm), MHA (no GQA), GELU MLP (not SwiGLU), tied embeddings.

Same TPU-first layout as ``models/llama.py``: stacked per-layer params scanned
with ``lax.scan``, bf16 compute / fp32 params, partition rules over the named
mesh axes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .llama import _dequant_layer, _sp_active, cross_entropy, labels_and_weights
from .llama import sp_attention as _sp_attention
from ..parallel.sharding import constrain as _constrain, embed_lookup as _embed_lookup

__all__ = ["GPT2Config", "init_params", "apply", "loss_fn", "PARTITION_RULES", "param_specs"]


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    layer_norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    # "chunked" streams the (tied) LM-head loss over vocab tiles
    # (ops/chunked_ce.py) — at GPT-2's 50257 vocab the dense fp32 logits are
    # the single largest activation; same knob as LlamaConfig.loss_impl.
    loss_impl: str = "dense"
    loss_chunk_size: int = 4096
    # Sequence parallelism: with an sp>1 mesh axis, attention runs the shared
    # ring/ulysses machinery (same knob as LlamaConfig.sp_impl) instead of
    # materializing the [B, S, S] mask — which is what makes long context
    # feasible on this family too.
    sp_impl: str = "ring"
    # int8 KV cache for generation (shared machinery; see LlamaConfig).
    kv_cache_quant: bool = False

    def __post_init__(self):
        if self.loss_impl not in ("dense", "chunked"):
            raise ValueError(f"loss_impl must be 'dense' or 'chunked', got {self.loss_impl!r}")
        if self.sp_impl not in ("ring", "ulysses"):
            raise ValueError(f"sp_impl must be 'ring' or 'ulysses', got {self.sp_impl!r}")

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def intermediate_size(self) -> int:
        return 4 * self.hidden_size

    @classmethod
    def tiny(cls, **kw) -> "GPT2Config":
        defaults = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                        max_seq_len=128, remat=False)
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def gpt2_small(cls, **kw) -> "GPT2Config":
        return cls(**kw)

    def num_params(self) -> int:
        d, v, l = self.hidden_size, self.vocab_size, self.num_layers
        attn = 3 * d * d + 3 * d + d * d + d  # qkv + proj with biases
        mlp = d * 4 * d + 4 * d + 4 * d * d + d
        norms = 4 * d
        return l * (attn + mlp + norms) + v * d + self.max_seq_len * d + 2 * d


PARTITION_RULES: list[tuple[str, P]] = [
    (r"wte", P("tp", "fsdp")),
    (r"wpe", P(None, "fsdp")),
    (r"layers/w_qkv", P(None, "fsdp", "tp")),
    (r"layers/w_proj", P(None, "tp", "fsdp")),
    (r"layers/w_up", P(None, "fsdp", "tp")),
    (r"layers/w_down", P(None, "tp", "fsdp")),
    (r"layers/(b_|ln_)", P(None, None)),
    (r"final_ln", P(None)),
]


def _param_shapes(c: GPT2Config) -> dict:
    d, L = c.hidden_size, c.num_layers
    return {
        "wte": (c.vocab_size, d),
        "wpe": (c.max_seq_len, d),
        "layers": {
            "w_qkv": (L, d, 3 * d),
            "b_qkv": (L, 3 * d),
            "w_proj": (L, d, d),
            "b_proj": (L, d),
            "w_up": (L, d, 4 * d),
            "b_up": (L, 4 * d),
            "w_down": (L, 4 * d, d),
            "b_down": (L, d),
            "ln_attn_scale": (L, d),
            "ln_attn_bias": (L, d),
            "ln_mlp_scale": (L, d),
            "ln_mlp_bias": (L, d),
        },
        "final_ln_scale": (d,),
        "final_ln_bias": (d,),
    }


def param_specs(config: GPT2Config) -> dict:
    from ..parallel.sharding import spec_from_rules

    shapes = _param_shapes(config)

    def one(kp, shape):
        path = "/".join(str(getattr(k, "key", k)) for k in kp)
        spec = spec_from_rules(path, len(shape), PARTITION_RULES)
        return spec if spec is not None else P(*([None] * len(shape)))

    return jax.tree_util.tree_map_with_path(one, shapes, is_leaf=lambda x: isinstance(x, tuple))


def init_params(config: GPT2Config, key: jax.Array) -> dict:
    shapes = _param_shapes(config)
    leaves, treedef = jax.tree_util.tree_flatten(shapes, is_leaf=lambda x: isinstance(x, tuple))
    keys = jax.tree_util.tree_unflatten(treedef, list(jax.random.split(key, len(leaves))))

    def init_one(kp, shape, k):
        # Name-based dispatch (see llama.init_params): a shape test would zero
        # the (max_seq_len, d) position table whenever max_seq_len == num_layers.
        # Scales to 1, biases to 0, weights normal(0.02) (GPT-2 init).
        name = str(getattr(kp[-1], "key", kp[-1]))
        if name.endswith("_scale"):
            return jnp.ones(shape, config.param_dtype)
        if name.startswith("b_") or name.endswith("_bias"):
            return jnp.zeros(shape, config.param_dtype)
        return (jax.random.normal(k, shape, jnp.float32) * 0.02).astype(config.param_dtype)

    return jax.tree_util.tree_map_with_path(
        init_one, shapes, keys, is_leaf=lambda x: isinstance(x, tuple)
    )


def _layer_norm(x, scale, bias, eps):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean((x32 - mean) ** 2, axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return y.astype(x.dtype) * scale.astype(x.dtype) + bias.astype(x.dtype)


def _qkv(x, p, c: GPT2Config):
    """Pre-norm fused QKV projection -> q, k, v ``[B, S, H, hd]``."""
    b, s, _ = x.shape
    hn = _layer_norm(x, p["ln_attn_scale"], p["ln_attn_bias"], c.layer_norm_eps)
    qkv = hn @ p["w_qkv"].astype(c.dtype) + p["b_qkv"].astype(c.dtype)
    q, k, v = jnp.split(qkv.reshape(b, s, 3, c.num_heads, c.head_dim), 3, axis=2)
    return q[:, :, 0], k[:, :, 0], v[:, :, 0]


def _attend(q, k, v, mask, c: GPT2Config):
    """Masked softmax attention; mask broadcasts against ``[B, H, Sq, Sk]``."""
    b, s = q.shape[:2]
    scores = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32) / np.sqrt(c.head_dim)
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bhst,bthd->bshd", probs, v).reshape(b, s, c.hidden_size)


def _mlp_block(x, p, c: GPT2Config):
    hn = _layer_norm(x, p["ln_mlp_scale"], p["ln_mlp_bias"], c.layer_norm_eps)
    u = jax.nn.gelu(hn @ p["w_up"].astype(c.dtype) + p["b_up"].astype(c.dtype))
    return x + u @ p["w_down"].astype(c.dtype) + p["b_down"].astype(c.dtype)


def _layer(carry, p, *, c: GPT2Config, mask, kv_valid=None, act_spec):
    x = carry
    b, s, _ = x.shape
    q, k, v = _qkv(x, p, c)
    if _sp_active():
        # Sequence-parallel path: the shared dispatch (ring / ulysses, with
        # the fused-Pallas fast paths) — causal at block granularity, the
        # [B, S] validity vector rides the ring; never a global [S, S] mask.
        attn = _sp_attention(q, k, v, c, causal=True, kv_valid=kv_valid)
        attn = attn.reshape(b, s, c.hidden_size)
    else:
        attn = _attend(q, k, v, mask[:, None], c)
    x = x + attn @ p["w_proj"].astype(c.dtype) + p["b_proj"].astype(c.dtype)
    x = _mlp_block(x, p, c)
    if act_spec is not None:
        x = _constrain(x, act_spec)
    return x, None


def lm_head(params: dict, config: GPT2Config) -> jax.Array:
    """The tied [d, V] head (wte transposed) in compute dtype — single source
    for apply() and the chunked loss (mirrors llama.lm_head)."""
    return params["wte"].astype(config.dtype).T


def apply(
    params: dict,
    input_ids: jax.Array,
    config: GPT2Config,
    attention_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Token ids [B, S] -> fp32 logits [B, S, V] (tied lm head)."""
    hidden = apply_hidden(params, input_ids, config, attention_mask)
    return (hidden @ lm_head(params, config)).astype(jnp.float32)


def apply_hidden(
    params: dict,
    input_ids: jax.Array,
    config: GPT2Config,
    attention_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Trunk forward -> final-LN hidden [B, S, d] (compute dtype)."""
    c = config
    b, s = input_ids.shape
    kv_valid = attention_mask.astype(bool) if attention_mask is not None else None
    if _sp_active():
        mask = None  # the sp path masks causally per block; no [S, S] tensor
    else:
        mask = jnp.broadcast_to(jnp.tril(jnp.ones((s, s), bool)), (b, s, s))
        if kv_valid is not None:
            mask = mask & kv_valid[:, None, :]

    x = _embed_lookup(params["wte"], input_ids, c.dtype) + params["wpe"].astype(c.dtype)[:s][None]
    act_spec = P(("dcn_dp", "dp", "fsdp"), "sp", None)
    x = _constrain(x, act_spec)

    def body(carry, lp):
        return _layer(carry, _dequant_layer(lp), c=c, mask=mask, kv_valid=kv_valid,
                      act_spec=act_spec)

    if c.remat:
        body = jax.checkpoint(body, policy=jax.checkpoint_policies.nothing_saveable)
    x, _ = jax.lax.scan(body, x, params["layers"])
    return _layer_norm(x, params["final_ln_scale"], params["final_ln_bias"], c.layer_norm_eps)


def loss_fn(params: dict, batch: dict, config: GPT2Config) -> jax.Array:
    labels, weights = labels_and_weights(batch)
    if config.loss_impl == "chunked":
        from ..ops.chunked_ce import chunked_cross_entropy

        hidden = apply_hidden(
            params, batch["input_ids"], config, attention_mask=batch.get("attention_mask")
        )
        return chunked_cross_entropy(
            hidden, lm_head(params, config), labels, weights, config.loss_chunk_size
        )
    logits = apply(params, batch["input_ids"], config, attention_mask=batch.get("attention_mask"))
    return cross_entropy(logits, labels, weights)


# ---------------------------------------------------------------------------
# KV-cache inference (shared driver: models/generation.py)
# ---------------------------------------------------------------------------


def quantize_weights(params: dict, block_size: int = 64) -> dict:
    """int8-weight-resident storage for the stacked blocks (wte/wpe and
    per-layer norms/biases stay full precision); see
    ``llama.quantize_weights``."""
    from ..utils.quantization import quantize_layer_stack

    out = dict(params)
    out["layers"] = quantize_layer_stack(params["layers"], block_size)
    return out


def init_cache(config: GPT2Config, batch_size: int, max_len: int) -> dict:
    """Zeroed KV cache: k/v ``[L, B, max_len, H, hd]`` + write index."""
    from .generation import make_kv_cache

    c = config
    return make_kv_cache(
        c.num_layers, batch_size, max_len, c.num_heads, c.head_dim, c.dtype,
        quantized=c.kv_cache_quant,
    )


def apply_cached(
    params: dict,
    input_ids: jax.Array,
    config: GPT2Config,
    cache: dict,
) -> tuple[jax.Array, dict]:
    """Forward over new tokens at positions ``index..index+S`` with cache
    read/write; returns (logits [B, S, V], updated cache)."""
    c = config
    b, s = input_ids.shape
    from .generation import check_cache_room

    index = cache["index"]
    max_len = cache["k"].shape[2]
    check_cache_room(index, s, max_len)
    if max_len > c.max_seq_len:
        # wpe has max_seq_len rows; a longer cache would silently clamp the
        # position gather under jit and degrade output past the table edge.
        raise ValueError(
            f"cache length {max_len} exceeds max_seq_len {c.max_seq_len} "
            "(GPT-2's learned position table)"
        )

    positions = index + jnp.arange(s)
    x = _embed_lookup(params["wte"], input_ids, c.dtype) + params["wpe"].astype(c.dtype)[positions][None]

    k_pos = jnp.arange(max_len)
    mask = positions[:, None] >= k_pos[None, :]  # [S, max_len]

    from .generation import cache_write, pack_cache_for_scan, unpack_cache_from_scan

    def body(carry, xs):
        lp, ck, cv = xs
        lp = _dequant_layer(lp)
        x = carry
        q, k, v = _qkv(x, lp, c)
        ck, k_full = cache_write(ck, k, index, c.dtype)
        cv, v_full = cache_write(cv, v, index, c.dtype)
        attn = _attend(q, k_full, v_full, mask[None, None], c)
        x = x + attn @ lp["w_proj"].astype(c.dtype) + lp["b_proj"].astype(c.dtype)
        x = _mlp_block(x, lp, c)
        return x, (ck, cv)

    ck_in, cv_in, quant = pack_cache_for_scan(cache)
    x, (new_k, new_v) = jax.lax.scan(body, x, (params["layers"], ck_in, cv_in))
    x = _layer_norm(x, params["final_ln_scale"], params["final_ln_bias"], c.layer_norm_eps)
    logits = (x @ params["wte"].astype(c.dtype).T).astype(jnp.float32)
    return logits, unpack_cache_from_scan(new_k, new_v, index + s, quant)


def apply_paged(
    params: dict,
    input_ids: jax.Array,
    config: GPT2Config,
    pool: dict,
    tables: jax.Array,
    starts: jax.Array,
    kernel: bool = False,
) -> tuple[jax.Array, dict]:
    """Forward over new tokens straight against the paged block pool — the
    serving engine's decode/prefill fast path (no per-slot dense cache view
    is ever built or returned).

    Row ``b``'s tokens ``input_ids[b]`` sit at positions ``starts[b] ..
    starts[b]+T-1``; attention consumes pool K/V through the block tables
    ``tables [B, M]`` (``paged_cache_write``) and the freshly written rows
    come back as ``{leaf: [B, L, T, ...]}`` for the caller to scatter into
    the pool.  ``kernel=True`` routes fp decode through the Pallas
    paged-attention kernels (``ops/pallas_attention.py``): the single-token
    kernel at ``T == 1`` and the multi-token window kernel at ``T > 1`` (the
    speculative verify dispatch, where the T queries form a causal window at
    the cache tail — exactly this function's position/mask contract);
    int8 pools take the always-correct XLA path.  Prefill never passes
    ``kernel=True``."""
    from .generation import (
        pack_paged_pool_for_scan,
        paged_cache_write,
        unpack_paged_rows_from_scan,
    )

    c = config
    b, t = input_ids.shape
    pk_in, pv_in, quant = pack_paged_pool_for_scan(pool)
    bs = pool["k"].shape[2]
    total = tables.shape[1] * bs
    if total > c.max_seq_len:
        raise ValueError(
            f"block table extent {total} exceeds max_seq_len {c.max_seq_len} "
            "(GPT-2's learned position table)"
        )
    positions = starts[:, None].astype(jnp.int32) + jnp.arange(t, dtype=jnp.int32)[None]
    x = _embed_lookup(params["wte"], input_ids, c.dtype) + params["wpe"].astype(c.dtype)[positions]
    k_pos = jnp.arange(total, dtype=jnp.int32)
    mask = positions[:, :, None] >= k_pos[None, None, :]  # [B, T, M*bs]
    use_kernel = kernel and not quant
    if use_kernel:
        from ..ops.pallas_attention import pallas_available

        use_kernel = pallas_available()

    def body(carry, xs):
        if quant:
            lp, ck, cks, cv, cvs = xs
            pk, pv = (ck, cks), (cv, cvs)
        else:
            lp, pk, pv = xs
        lp = _dequant_layer(lp)
        x = carry
        q, k, v = _qkv(x, lp, c)
        if use_kernel:
            from ..ops.pallas_attention import (
                pallas_paged_attention,
                pallas_paged_window_attention,
            )

            k_store = k.astype(pk.dtype)
            v_store = v.astype(pv.dtype)
            if t == 1:
                attn = pallas_paged_attention(
                    q[:, 0], k_store[:, 0], v_store[:, 0], pk, pv, tables, starts
                )[:, None].reshape(b, t, c.hidden_size)
            else:
                attn = pallas_paged_window_attention(
                    q, k_store, v_store, pk, pv, tables, starts
                ).reshape(b, t, c.hidden_size)
        else:
            k_store, k_full = paged_cache_write(pk, k, tables, starts, c.dtype)
            v_store, v_full = paged_cache_write(pv, v, tables, starts, c.dtype)
            attn = _attend(q, k_full, v_full, mask[:, None], c)
        x = x + attn @ lp["w_proj"].astype(c.dtype) + lp["b_proj"].astype(c.dtype)
        x = _mlp_block(x, lp, c)
        return x, (k_store, v_store)

    xs = (params["layers"],) + (
        (pool["k"], pool["k_scale"], pool["v"], pool["v_scale"]) if quant
        else (pool["k"], pool["v"])
    )
    x, (k_rows, v_rows) = jax.lax.scan(body, x, xs)
    x = _layer_norm(x, params["final_ln_scale"], params["final_ln_bias"], c.layer_norm_eps)
    logits = (x @ params["wte"].astype(c.dtype).T).astype(jnp.float32)
    return logits, unpack_paged_rows_from_scan(k_rows, v_rows, quant)


def generate(
    params: dict,
    input_ids: jax.Array,
    config: GPT2Config,
    max_new_tokens: int,
    temperature: float = 0.0,
    key=None,
    max_len=None,
    top_k: int = 0,
    top_p: float = 1.0,
    prefill_chunk=None,
) -> jax.Array:
    """Autoregressive generation (one compiled XLA program; see
    models/generation.py)."""
    from .generation import generate_loop

    return generate_loop(
        apply_cached, init_cache, params, input_ids, config,
        max_new_tokens, temperature=temperature, key=key, max_len=max_len,
        top_k=top_k, top_p=top_p, prefill_chunk=prefill_chunk,
    )


def speculative_generate(
    params: dict,
    draft_params: dict,
    input_ids: jax.Array,
    config: GPT2Config,
    draft_config: GPT2Config,
    max_new_tokens: int,
    num_draft_tokens: int = 4,
    max_len=None,
    return_stats: bool = False,
    temperature: float = 0.0,
    key=None,
) -> jax.Array:
    """Speculative decoding (see ``models/generation.py``): greedy by
    default (token-identical to ``generate(..., temperature=0)``), or the
    distribution-exact rejection-sampling mode with ``temperature>0`` +
    ``key``.  Batch 1 only.  The cache slack (prompt + new +
    num_draft_tokens) must fit the position table (``config.max_seq_len``)."""
    from .generation import speculative_generate_loop

    return speculative_generate_loop(
        apply_cached, init_cache, params, config,
        apply_cached, init_cache, draft_params, draft_config,
        input_ids, max_new_tokens,
        num_draft_tokens=num_draft_tokens, max_len=max_len,
        return_stats=return_stats, temperature=temperature, key=key,
    )


def generate_beam(
    params: dict,
    input_ids: jax.Array,
    config: GPT2Config,
    max_new_tokens: int,
    num_beams: int = 4,
    length_penalty: float = 1.0,
    eos_token_id=None,
    max_len=None,
) -> jax.Array:
    """Beam-search generation (see ``models/generation.py beam_search``)."""
    from .generation import beam_search

    return beam_search(
        apply_cached, init_cache, params, input_ids, config, max_new_tokens,
        num_beams=num_beams, length_penalty=length_penalty,
        eos_token_id=eos_token_id, max_len=max_len,
    )
