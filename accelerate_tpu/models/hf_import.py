"""HF-checkpoint import: transformers state dicts -> native param trees.

Parity rationale: the reference ecosystem loads models with
``transformers.from_pretrained`` and hands them to Accelerate
(reference ``examples/nlp_example.py``, big-model path
``utils/modeling.py:1783`` streaming shards into a torch module).  The
native families here are pure pytrees, so the equivalent is a
*weight-mapping* layer: take a transformers model (or its state dict) and
produce the native ``(config, params)`` pair that `apply`/`generate`/
`loss_fn` consume — no torch in the compute path afterwards.

Supported families and their HF architectures:

- ``llama``   — LlamaForCausalLM / LlamaModel (HF rotate-half RoPE matches
                the native `_rope`; torch Linear weights are [out, in] and
                transpose to the native [in, out] matmul layout) — plus
                Qwen2ForCausalLM (the same architecture with Q/K/V biases,
                ``LlamaConfig(attention_bias=True)``), MistralForCausalLM
                (llama-shaped GQA, v0.2+; sliding-window configs refused),
                GemmaForCausalLM (GeGLU + (1+w) RMSNorm + sqrt(d)
                embeddings via the ``hidden_act``/``rms_offset``/
                ``embed_scale`` knobs), Phi3ForCausalLM (fused
                qkv_proj/gate_up_proj split on import), and Llama-3.1
                ``rope_scaling`` (the llama3 long-context rule)
- ``gpt2``    — GPT2LMHeadModel / GPT2Model (Conv1D stores [in, out]:
                no transpose; wte is tied as the unembedding)
- ``bert``    — BertForSequenceClassification / BertModel (post-LN; note
                the native family computes tanh-approximate GeLU — HF's
                erf GeLU differs at ~1e-3 activations)
- ``t5``      — T5ForConditionalGeneration / T5Model (no attention scaling,
                relative-position bias from block 0, tied shared embedding
                with the 1/sqrt(d) output rescale)
- ``mixtral`` — MixtralForCausalLM (experts w1/w3/w2 -> gate/up/down
                stacked [L, E, ...]; the router gate maps transposed)
- ``vit``     — ViTForImageClassification / ViTModel (patch-conv kernel
                [d, C, p, p] -> the patchify matmul's [p*p*C, d])
- ``resnet``  — ResNetForImageClassification / ResNetModel (HF's v1.5
                blocks = the native layout; conv kernels OIHW -> HWIO; BN
                running statistics import as a ``batch_stats`` tree next to
                ``params`` — this family's import returns
                ``{"params": ..., "batch_stats": ...}``)

Every tensor is copied through numpy (no torch object survives into the
pytree).  Tested by logits-parity oracles against the actual transformers
forward on randomly initialized tiny models (``tests/test_hf_import.py``).
"""

from __future__ import annotations

import re
from typing import Any, Optional

import numpy as np

import jax.numpy as jnp

__all__ = ["config_from_hf", "import_state_dict", "from_hf", "load_hf_checkpoint"]


def _np(t) -> np.ndarray:
    """torch tensor / array-like -> float32 numpy (detached, host)."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t, np.float32)


def _stack(sd: dict, fmt: str, n: int, transpose: bool = False) -> np.ndarray:
    """Stack per-layer tensors ``fmt.format(i)`` into [L, ...]."""
    mats = [_np(sd[fmt.format(i)]) for i in range(n)]
    if transpose:
        mats = [m.T for m in mats]
    return np.stack(mats)


def _stack_cat(sd: dict, fmts: list, n: int, transpose: bool = False) -> np.ndarray:
    """Per layer, concat several tensors along the last axis, then stack —
    the fused-QKV layout ([Wq | Wk | Wv] along the output dim)."""
    out = []
    for i in range(n):
        mats = [_np(sd[f.format(i)]) for f in fmts]
        if transpose:
            mats = [m.T for m in mats]
        out.append(np.concatenate(mats, axis=-1))
    return np.stack(out)


def _detect_family(hf_config) -> str:
    mt = getattr(hf_config, "model_type", "")
    known = {"llama", "gpt2", "bert", "t5", "mixtral", "vit", "resnet"}
    if mt in ("qwen2", "mistral", "gemma", "phi3"):
        # llama-architecture variants: qwen2 adds Q/K/V biases, mistral is
        # llama-shaped GQA, gemma swaps in GeGLU + (1+w) RMSNorm + sqrt(d)
        # embeddings, phi3 fuses qkv_proj/gate_up_proj (split on import) —
        # all map onto the llama family; sliding-window, gemma2 and
        # longrope configs are refused in config_from_hf.
        return "llama"
    if mt in known:
        return mt
    raise ValueError(
        f"Unsupported HF model_type {mt!r}; supported: {sorted(known)} "
        "(qwen2, mistral, gemma and phi3 map onto llama)"
    )


def config_from_hf(hf_config, **overrides):
    """Build the native config dataclass from a transformers config."""
    family = _detect_family(hf_config)
    c = hf_config
    if family == "llama":
        from .llama import LlamaConfig

        mt = getattr(c, "model_type", "llama")
        if mt == "qwen2" and getattr(c, "use_sliding_window", False):
            raise ValueError(
                "qwen2 import requires use_sliding_window=False: the native "
                "attention paths are full-causal."
            )
        if mt == "mistral" and getattr(c, "sliding_window", None) is not None:
            raise ValueError(
                "mistral import requires sliding_window=null (v0.2+ configs): "
                "the native attention paths are full-causal, so a windowed "
                "checkpoint would silently attend differently."
            )
        if mt == "phi3":
            if getattr(c, "sliding_window", None) is not None:
                raise ValueError(
                    "phi3 import requires sliding_window=null: the native "
                    "attention paths are full-causal."
                )
            if float(getattr(c, "partial_rotary_factor", 1.0)) != 1.0:
                raise ValueError(
                    "phi3 import requires partial_rotary_factor=1.0 (the "
                    "native RoPE rotates the full head dim)."
                )
        # llama checkpoints default attention_bias False; qwen2's bias is
        # architectural (always on — transformers hardcodes it, so a stray
        # "attention_bias": false in a qwen2 config.json must not win).
        bias = True if mt == "qwen2" else bool(getattr(c, "attention_bias", False))
        rs = getattr(c, "rope_scaling", None)
        rope_scaling = None
        if rs:
            rs = dict(rs)
            kind = rs.get("rope_type", rs.get("type"))
            if kind == "default":  # transformers: plain unscaled RoPE
                kind = None
                rs = None
            elif kind != "llama3":
                raise ValueError(
                    f"rope_scaling type {kind!r} is not supported (llama3 "
                    "long-context rescaling only); importing would silently "
                    "rotate positions differently from the checkpoint."
                )
        if rs:
            rope_scaling = (
                "llama3",
                float(rs["factor"]),
                float(rs["low_freq_factor"]),
                float(rs["high_freq_factor"]),
                int(rs["original_max_position_embeddings"]),
            )
        gemma = mt == "gemma"
        if not gemma and getattr(c, "hidden_act", "silu") != "silu":
            raise ValueError(
                f"{mt} import supports hidden_act='silu', got "
                f"{c.hidden_act!r}; the native MLP would silently compute a "
                "different activation."
            )
        if gemma:
            # transformers overrides legacy configs (hidden_activation=None)
            # to gelu_pytorch_tanh; an EXPLICIT hidden_activation that is not
            # the tanh variant (e.g. exact-erf 'gelu') would silently diverge
            # from the native tanh-approximate path — refuse it.
            act_explicit = getattr(c, "hidden_activation", None)
            if act_explicit is not None and act_explicit != "gelu_pytorch_tanh":
                raise ValueError(
                    "gemma import supports hidden_activation="
                    f"'gelu_pytorch_tanh' (or unset), got {act_explicit!r}"
                )
        kw = dict(
            vocab_size=c.vocab_size,
            hidden_size=c.hidden_size,
            intermediate_size=c.intermediate_size,
            num_layers=c.num_hidden_layers,
            num_heads=c.num_attention_heads,
            num_kv_heads=getattr(c, "num_key_value_heads", c.num_attention_heads),
            head_dim=getattr(c, "head_dim", None),
            max_seq_len=c.max_position_embeddings,
            rope_theta=float(getattr(c, "rope_theta", 10000.0)),
            rms_eps=float(c.rms_norm_eps),
            tie_embeddings=bool(getattr(c, "tie_word_embeddings", gemma)),
            attention_bias=bias,
            hidden_act="gelu_tanh" if gemma else "silu",
            rms_offset=gemma,
            embed_scale=gemma,
            rope_scaling=rope_scaling,
        )
        kw.update(overrides)
        return LlamaConfig(**kw)
    if family == "gpt2":
        from .gpt2 import GPT2Config

        kw = dict(
            vocab_size=c.vocab_size,
            hidden_size=c.n_embd,
            num_layers=c.n_layer,
            num_heads=c.n_head,
            max_seq_len=c.n_positions,
            layer_norm_eps=float(c.layer_norm_epsilon),
        )
        kw.update(overrides)
        return GPT2Config(**kw)
    if family == "bert":
        from .bert import BertConfig

        kw = dict(
            vocab_size=c.vocab_size,
            hidden_size=c.hidden_size,
            num_layers=c.num_hidden_layers,
            num_heads=c.num_attention_heads,
            max_seq_len=c.max_position_embeddings,
            type_vocab_size=c.type_vocab_size,
            num_labels=getattr(c, "num_labels", 2),
            layer_norm_eps=float(c.layer_norm_eps),
        )
        kw.update(overrides)
        return BertConfig(**kw)
    if family == "t5":
        from .t5 import T5Config

        # The native T5 always unembeds through the 1/sqrt(d)-scaled shared
        # embedding and applies plain ReLU; importing a checkpoint with a
        # separate lm_head or a gated activation would run but produce wrong
        # logits — refuse loudly instead.
        if not getattr(c, "tie_word_embeddings", True):
            raise ValueError(
                "T5 import requires tie_word_embeddings=True (the native "
                "family unembeds through the shared embedding)."
            )
        ff = getattr(c, "feed_forward_proj", "relu")
        if ff not in ("relu",):
            raise ValueError(
                f"T5 import supports feed_forward_proj='relu' only, got {ff!r} "
                "(gated variants have extra wi_0/wi_1 tensors the native "
                "family does not model)."
            )
        ndl = getattr(c, "num_decoder_layers", None)
        if ndl is not None and ndl != c.num_layers:
            raise ValueError(
                f"T5 import requires num_decoder_layers == num_layers "
                f"(got {ndl} vs {c.num_layers}); the native family uses one "
                "depth per stack."
            )
        kw = dict(
            vocab_size=c.vocab_size,
            hidden_size=c.d_model,
            intermediate_size=c.d_ff,
            num_layers=c.num_layers,
            num_heads=c.num_heads,
            head_dim=c.d_kv,
            num_buckets=c.relative_attention_num_buckets,
            max_distance=getattr(c, "relative_attention_max_distance", 128),
            rms_eps=float(c.layer_norm_epsilon),
        )
        kw.update(overrides)
        return T5Config(**kw)
    if family == "mixtral":
        from .mixtral import MixtralConfig

        kw = dict(
            vocab_size=c.vocab_size,
            hidden_size=c.hidden_size,
            intermediate_size=c.intermediate_size,
            num_layers=c.num_hidden_layers,
            num_heads=c.num_attention_heads,
            num_kv_heads=c.num_key_value_heads,
            num_experts=c.num_local_experts,
            top_k=c.num_experts_per_tok,
            max_seq_len=c.max_position_embeddings,
            rope_theta=float(getattr(c, "rope_theta", 1e6)),
            rms_eps=float(c.rms_norm_eps),
        )
        kw.update(overrides)
        return MixtralConfig(**kw)
    if family == "resnet":
        from .resnet import ResNetConfig

        block = {"bottleneck": "bottleneck", "basic": "basic"}.get(
            getattr(c, "layer_type", "bottleneck")
        )
        if block is None:
            raise ValueError(f"Unsupported resnet layer_type {c.layer_type!r}")
        if getattr(c, "downsample_in_first_stage", False):
            raise ValueError(
                "resnet import requires downsample_in_first_stage=False "
                "(the native family strides stage 0 at 1, torchvision-style)."
            )
        if getattr(c, "downsample_in_bottleneck", False):
            raise ValueError(
                "resnet import requires downsample_in_bottleneck=False: the "
                "native block strides the 3x3 conv (v1.5); a v1-style "
                "checkpoint (stride on the first 1x1) has identical shapes "
                "but different numerics, so it must be refused, not silently "
                "mis-run."
            )
        width = c.embedding_size
        e = 4 if block == "bottleneck" else 1
        expect = [width * (2**s) * e for s in range(len(c.depths))]
        if list(c.hidden_sizes) != expect:
            raise ValueError(
                f"resnet import supports the standard doubling geometry "
                f"(hidden_sizes {expect} for embedding_size {width}); got "
                f"{list(c.hidden_sizes)}."
            )
        kw = dict(
            block=block,
            stage_sizes=tuple(c.depths),
            width=width,
            num_labels=getattr(c, "num_labels", 2),
            stem="imagenet",
        )
        kw.update(overrides)
        return ResNetConfig(**kw)
    # vit
    from .vit import ViTConfig

    kw = dict(
        image_size=c.image_size,
        patch_size=c.patch_size,
        num_channels=c.num_channels,
        hidden_size=c.hidden_size,
        num_layers=c.num_hidden_layers,
        num_heads=c.num_attention_heads,
        mlp_ratio=c.intermediate_size // c.hidden_size,
        num_labels=getattr(c, "num_labels", 2),
        layer_norm_eps=float(c.layer_norm_eps),
    )
    kw.update(overrides)
    return ViTConfig(**kw)


def _strip_prefix(sd: dict, prefixes: tuple) -> dict:
    """Drop an architecture wrapper prefix ('model.', 'transformer.', ...) so
    ForCausalLM / bare-Model state dicts map identically."""
    for p in prefixes:
        if any(k.startswith(p) for k in sd):
            return {
                (k[len(p):] if k.startswith(p) else k): v for k, v in sd.items()
            }
    return sd


def _import_llama(sd: dict, cfg) -> dict:
    L = cfg.num_layers
    pre = "layers.{}."
    if "layers.0.self_attn.qkv_proj.weight" in sd:
        # phi3 fuses the projections ([q|k|v] rows, [gate|up] rows): split
        # per layer back into the separate native tensors.
        nq = cfg.num_heads * cfg.head_dim_
        nk = cfg.num_kv_heads * cfg.head_dim_
        f = cfg.intermediate_size
        wq, wk, wv, wg, wu = [], [], [], [], []
        for i in range(L):
            qkv = _np(sd[f"layers.{i}.self_attn.qkv_proj.weight"])
            wq.append(qkv[:nq].T.copy())
            wk.append(qkv[nq:nq + nk].T.copy())
            wv.append(qkv[nq + nk:].T.copy())
            gu = _np(sd[f"layers.{i}.mlp.gate_up_proj.weight"])
            wg.append(gu[:f].T.copy())
            wu.append(gu[f:].T.copy())
        attn = {
            "wq": np.stack(wq), "wk": np.stack(wk), "wv": np.stack(wv),
            "w_gate": np.stack(wg), "w_up": np.stack(wu),
        }
    else:
        attn = {
            "wq": _stack(sd, pre + "self_attn.q_proj.weight", L, transpose=True),
            "wk": _stack(sd, pre + "self_attn.k_proj.weight", L, transpose=True),
            "wv": _stack(sd, pre + "self_attn.v_proj.weight", L, transpose=True),
            "w_gate": _stack(sd, pre + "mlp.gate_proj.weight", L, transpose=True),
            "w_up": _stack(sd, pre + "mlp.up_proj.weight", L, transpose=True),
        }
    params = {
        "embed": _np(sd["embed_tokens.weight"]),
        "layers": {
            **attn,
            "wo": _stack(sd, pre + "self_attn.o_proj.weight", L, transpose=True),
            "w_down": _stack(sd, pre + "mlp.down_proj.weight", L, transpose=True),
            "ln_attn": _stack(sd, pre + "input_layernorm.weight", L),
            "ln_mlp": _stack(sd, pre + "post_attention_layernorm.weight", L),
        },
        "final_norm": _np(sd["norm.weight"]),
    }
    if cfg.attention_bias:
        params["layers"]["bq"] = _stack(sd, pre + "self_attn.q_proj.bias", L)
        params["layers"]["bk"] = _stack(sd, pre + "self_attn.k_proj.bias", L)
        params["layers"]["bv"] = _stack(sd, pre + "self_attn.v_proj.bias", L)
        # HF llama with attention_bias also biases o_proj; qwen2 does not —
        # zeros are numerically identical to "no bias".
        if "layers.0.self_attn.o_proj.bias" in sd:
            params["layers"]["bo"] = _stack(sd, pre + "self_attn.o_proj.bias", L)
        else:
            params["layers"]["bo"] = np.zeros(
                (L, cfg.hidden_size), np.float32
            )
    head = sd.get("lm_head.weight")  # consumed even when tied (alias)
    if not cfg.tie_embeddings:
        params["lm_head"] = (
            _np(head).T if head is not None else params["embed"].T.copy()
        )
    return params


def _import_gpt2(sd: dict, cfg) -> dict:
    sd.get("lm_head.weight")  # tied alias of wte; consume it
    L = cfg.num_layers
    pre = "h.{}."
    return {
        "wte": _np(sd["wte.weight"]),
        "wpe": _np(sd["wpe.weight"]),
        "layers": {
            # HF GPT-2 uses Conv1D ([in, out] storage): no transpose.
            "w_qkv": _stack(sd, pre + "attn.c_attn.weight", L),
            "b_qkv": _stack(sd, pre + "attn.c_attn.bias", L),
            "w_proj": _stack(sd, pre + "attn.c_proj.weight", L),
            "b_proj": _stack(sd, pre + "attn.c_proj.bias", L),
            "w_up": _stack(sd, pre + "mlp.c_fc.weight", L),
            "b_up": _stack(sd, pre + "mlp.c_fc.bias", L),
            "w_down": _stack(sd, pre + "mlp.c_proj.weight", L),
            "b_down": _stack(sd, pre + "mlp.c_proj.bias", L),
            "ln_attn_scale": _stack(sd, pre + "ln_1.weight", L),
            "ln_attn_bias": _stack(sd, pre + "ln_1.bias", L),
            "ln_mlp_scale": _stack(sd, pre + "ln_2.weight", L),
            "ln_mlp_bias": _stack(sd, pre + "ln_2.bias", L),
        },
        "final_ln_scale": _np(sd["ln_f.weight"]),
        "final_ln_bias": _np(sd["ln_f.bias"]),
    }


def _import_bert(sd: dict, cfg) -> dict:
    L = cfg.num_layers
    pre = "encoder.layer.{}."
    qkv_w = [pre + f"attention.self.{n}.weight" for n in ("query", "key", "value")]
    qkv_b = [pre + f"attention.self.{n}.bias" for n in ("query", "key", "value")]
    d = cfg.hidden_size
    params = {
        "embeddings": {
            "word": _np(sd["embeddings.word_embeddings.weight"]),
            "position": _np(sd["embeddings.position_embeddings.weight"]),
            "token_type": _np(sd["embeddings.token_type_embeddings.weight"]),
            "ln_scale": _np(sd["embeddings.LayerNorm.weight"]),
            "ln_bias": _np(sd["embeddings.LayerNorm.bias"]),
        },
        "layers": {
            "w_qkv": _stack_cat(sd, qkv_w, L, transpose=True),
            "b_qkv": _stack_cat(sd, qkv_b, L),
            "w_proj": _stack(sd, pre + "attention.output.dense.weight", L, transpose=True),
            "b_proj": _stack(sd, pre + "attention.output.dense.bias", L),
            "w_up": _stack(sd, pre + "intermediate.dense.weight", L, transpose=True),
            "b_up": _stack(sd, pre + "intermediate.dense.bias", L),
            "w_down": _stack(sd, pre + "output.dense.weight", L, transpose=True),
            "b_down": _stack(sd, pre + "output.dense.bias", L),
            "ln_attn_scale": _stack(sd, pre + "attention.output.LayerNorm.weight", L),
            "ln_attn_bias": _stack(sd, pre + "attention.output.LayerNorm.bias", L),
            "ln_mlp_scale": _stack(sd, pre + "output.LayerNorm.weight", L),
            "ln_mlp_bias": _stack(sd, pre + "output.LayerNorm.bias", L),
        },
    }
    if "pooler.dense.weight" in sd:
        params["pooler"] = {
            "w": _np(sd["pooler.dense.weight"]).T,
            "b": _np(sd["pooler.dense.bias"]),
        }
    else:
        params["pooler"] = {"w": np.zeros((d, d), np.float32),
                            "b": np.zeros((d,), np.float32)}
    if "classifier.weight" in sd:
        params["classifier"] = {
            "w": _np(sd["classifier.weight"]).T,
            "b": _np(sd["classifier.bias"]),
        }
    else:
        params["classifier"] = {
            "w": np.zeros((d, cfg.num_labels), np.float32),
            "b": np.zeros((cfg.num_labels,), np.float32),
        }
    return params


def _import_t5_stack(sd: dict, cfg, stack: str) -> dict:
    L = cfg.num_layers
    pre = f"{stack}.block.{{}}."
    out = {
        "wq": _stack(sd, pre + "layer.0.SelfAttention.q.weight", L, transpose=True),
        "wk": _stack(sd, pre + "layer.0.SelfAttention.k.weight", L, transpose=True),
        "wv": _stack(sd, pre + "layer.0.SelfAttention.v.weight", L, transpose=True),
        "wo": _stack(sd, pre + "layer.0.SelfAttention.o.weight", L, transpose=True),
        "ln_attn": _stack(sd, pre + "layer.0.layer_norm.weight", L),
    }
    mlp_idx = 2 if stack == "decoder" else 1
    out["w_up"] = _stack(
        sd, pre + f"layer.{mlp_idx}.DenseReluDense.wi.weight", L, transpose=True
    )
    out["w_down"] = _stack(
        sd, pre + f"layer.{mlp_idx}.DenseReluDense.wo.weight", L, transpose=True
    )
    out["ln_mlp"] = _stack(sd, pre + f"layer.{mlp_idx}.layer_norm.weight", L)
    if stack == "decoder":
        out["cross_wq"] = _stack(
            sd, pre + "layer.1.EncDecAttention.q.weight", L, transpose=True
        )
        out["cross_wk"] = _stack(
            sd, pre + "layer.1.EncDecAttention.k.weight", L, transpose=True
        )
        out["cross_wv"] = _stack(
            sd, pre + "layer.1.EncDecAttention.v.weight", L, transpose=True
        )
        out["cross_wo"] = _stack(
            sd, pre + "layer.1.EncDecAttention.o.weight", L, transpose=True
        )
        out["ln_cross"] = _stack(sd, pre + "layer.1.layer_norm.weight", L)
    return out


def _import_t5(sd: dict, cfg) -> dict:
    # Tied aliases of `shared.weight` that T5 serializes; consume them.
    sd.get("lm_head.weight")
    sd.get("encoder.embed_tokens.weight")
    sd.get("decoder.embed_tokens.weight")
    return {
        "shared_embed": _np(sd["shared.weight"]),
        "enc_rel_bias": _np(
            sd["encoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight"]
        ),
        "dec_rel_bias": _np(
            sd["decoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight"]
        ),
        "encoder": _import_t5_stack(sd, cfg, "encoder"),
        "decoder": _import_t5_stack(sd, cfg, "decoder"),
        "enc_final_ln": _np(sd["encoder.final_layer_norm.weight"]),
        "dec_final_ln": _np(sd["decoder.final_layer_norm.weight"]),
    }


def _import_mixtral(sd: dict, cfg) -> dict:
    L, E = cfg.num_layers, cfg.num_experts
    pre = "layers.{}."

    def experts(which: str) -> np.ndarray:
        per_layer = []
        for i in range(L):
            mats = [
                _np(sd[f"layers.{i}.block_sparse_moe.experts.{j}.{which}.weight"]).T
                for j in range(E)
            ]
            per_layer.append(np.stack(mats))
        return np.stack(per_layer)  # [L, E, in, out]

    params = {
        "embed": _np(sd["embed_tokens.weight"]),
        "layers": {
            "wq": _stack(sd, pre + "self_attn.q_proj.weight", L, transpose=True),
            "wk": _stack(sd, pre + "self_attn.k_proj.weight", L, transpose=True),
            "wv": _stack(sd, pre + "self_attn.v_proj.weight", L, transpose=True),
            "wo": _stack(sd, pre + "self_attn.o_proj.weight", L, transpose=True),
            "router": _stack(sd, pre + "block_sparse_moe.gate.weight", L, transpose=True),
            "w_gate": experts("w1"),
            "w_up": experts("w3"),
            "w_down": experts("w2"),
            "ln_attn": _stack(sd, pre + "input_layernorm.weight", L),
            "ln_mlp": _stack(sd, pre + "post_attention_layernorm.weight", L),
        },
        "final_norm": _np(sd["norm.weight"]),
    }
    head = sd.get("lm_head.weight")
    params["lm_head"] = (
        _np(head).T if head is not None else params["embed"].T.copy()
    )
    return params


def _import_vit(sd: dict, cfg) -> dict:
    L = cfg.num_layers
    p = cfg.patch_size
    pre = "encoder.layer.{}."
    qkv_w = [pre + f"attention.attention.{n}.weight" for n in ("query", "key", "value")]
    qkv_b = [pre + f"attention.attention.{n}.bias" for n in ("query", "key", "value")]
    conv = _np(sd["embeddings.patch_embeddings.projection.weight"])  # [d, C, p, p]
    d = conv.shape[0]
    # -> the patchify matmul layout: rows ordered (p_row, p_col, channel).
    patch_w = conv.transpose(2, 3, 1, 0).reshape(p * p * cfg.num_channels, d)
    emb = {
        "patch_w": patch_w,
        "patch_b": _np(sd["embeddings.patch_embeddings.projection.bias"]),
        "position": _np(sd["embeddings.position_embeddings"])[0],
    }
    if cfg.pool == "cls":
        emb["cls"] = _np(sd["embeddings.cls_token"])
    params = {
        "embeddings": emb,
        "layers": {
            "w_qkv": _stack_cat(sd, qkv_w, L, transpose=True),
            "b_qkv": _stack_cat(sd, qkv_b, L),
            "w_proj": _stack(sd, pre + "attention.output.dense.weight", L, transpose=True),
            "b_proj": _stack(sd, pre + "attention.output.dense.bias", L),
            "w_up": _stack(sd, pre + "intermediate.dense.weight", L, transpose=True),
            "b_up": _stack(sd, pre + "intermediate.dense.bias", L),
            "w_down": _stack(sd, pre + "output.dense.weight", L, transpose=True),
            "b_down": _stack(sd, pre + "output.dense.bias", L),
            "ln_attn_scale": _stack(sd, pre + "layernorm_before.weight", L),
            "ln_attn_bias": _stack(sd, pre + "layernorm_before.bias", L),
            "ln_mlp_scale": _stack(sd, pre + "layernorm_after.weight", L),
            "ln_mlp_bias": _stack(sd, pre + "layernorm_after.bias", L),
        },
        "final_ln": {
            "scale": _np(sd["layernorm.weight"]),
            "bias": _np(sd["layernorm.bias"]),
        },
    }
    if "classifier.weight" in sd:
        params["classifier"] = {
            "w": _np(sd["classifier.weight"]).T,
            "b": _np(sd["classifier.bias"]),
        }
    else:
        params["classifier"] = {
            "w": np.zeros((d, cfg.num_labels), np.float32),
            "b": np.zeros((cfg.num_labels,), np.float32),
        }
    return params


def _import_resnet(sd: dict, cfg) -> dict:
    """HF ResNet (v1.5: stride on the 3x3 — the native block layout) ->
    ``{"params": ..., "batch_stats": ...}``: BN running statistics are real
    state here, imported alongside the weights."""

    def conv(key):  # [O, I, kh, kw] -> HWIO
        return _np(sd[key]).transpose(2, 3, 1, 0).copy()

    def bn(prefix, site, params_out, stats_out):
        params_out[f"{site}_scale"] = _np(sd[prefix + ".weight"])
        params_out[f"{site}_bias"] = _np(sd[prefix + ".bias"])
        stats_out[f"{site}_mean"] = _np(sd[prefix + ".running_mean"])
        stats_out[f"{site}_var"] = _np(sd[prefix + ".running_var"])

    n_convs = 3 if cfg.block == "bottleneck" else 2
    params: dict = {"stem": {}}
    stats: dict = {"stem": {}}
    params["stem"]["conv_w"] = conv("embedder.embedder.convolution.weight")
    bn("embedder.embedder.normalization", "bn", params["stem"], stats["stem"])

    for s, depth in enumerate(cfg.stage_sizes):
        head_p: dict = {}
        head_s: dict = {}
        lp = f"encoder.stages.{s}.layers.0."
        for j in range(n_convs):
            head_p[f"conv{j + 1}_w"] = conv(lp + f"layer.{j}.convolution.weight")
            bn(lp + f"layer.{j}.normalization", f"bn{j + 1}", head_p, head_s)
        if lp + "shortcut.convolution.weight" in sd:
            head_p["proj_w"] = conv(lp + "shortcut.convolution.weight")
            bn(lp + "shortcut.normalization", "proj_bn", head_p, head_s)
        stage_p: dict = {"head": head_p}
        stage_s: dict = {"head": head_s}
        if depth > 1:
            tails_p = []
            tails_s = []
            for i in range(1, depth):
                tp: dict = {}
                ts: dict = {}
                lp = f"encoder.stages.{s}.layers.{i}."
                for j in range(n_convs):
                    tp[f"conv{j + 1}_w"] = conv(lp + f"layer.{j}.convolution.weight")
                    bn(lp + f"layer.{j}.normalization", f"bn{j + 1}", tp, ts)
                tails_p.append(tp)
                tails_s.append(ts)
            stage_p["tail"] = {
                k: np.stack([t[k] for t in tails_p]) for k in tails_p[0]
            }
            stage_s["tail"] = {
                k: np.stack([t[k] for t in tails_s]) for k in tails_s[0]
            }
        params[f"stage{s}"] = stage_p
        stats[f"stage{s}"] = stage_s

    d_out = cfg.stage_channels(len(cfg.stage_sizes) - 1) * cfg.expansion
    if "classifier.1.weight" in sd:
        params["classifier"] = {
            "w": _np(sd["classifier.1.weight"]).T.copy(),
            "b": _np(sd["classifier.1.bias"]),
        }
    else:
        params["classifier"] = {
            "w": np.zeros((d_out, cfg.num_labels), np.float32),
            "b": np.zeros((cfg.num_labels,), np.float32),
        }
    return {"params": params, "batch_stats": stats}


_IMPORTERS = {
    "llama": _import_llama,
    "gpt2": _import_gpt2,
    "bert": _import_bert,
    "t5": _import_t5,
    "mixtral": _import_mixtral,
    "vit": _import_vit,
    "resnet": _import_resnet,
}

# Architecture-wrapper prefixes stripped before mapping, so ForCausalLM /
# ForSequenceClassification / bare-Model state dicts all map identically.
_PREFIXES = {
    "llama": ("model.",),
    "gpt2": ("transformer.",),
    "bert": ("bert.",),
    "t5": (),
    "mixtral": ("model.",),
    "vit": ("vit.",),
    "resnet": ("resnet.",),
}


class _RecordingDict(dict):
    """Tracks which checkpoint keys an importer actually read, so silently
    dropped tensors (attention biases, extra heads, gated-MLP halves…)
    become a loud error instead of a wrong model.  Reads also *release* the
    source tensor (each weight is read exactly once), so the checkpoint dict
    shrinks as the staging pytree grows — peak host memory stays ~one model
    copy plus the tensor in flight, not checkpoint + full staging tree."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.consumed = set()

    def __getitem__(self, k):
        self.consumed.add(k)
        v = super().__getitem__(k)
        super().__delitem__(k)
        return v

    def get(self, k, default=None):
        if super().__contains__(k):
            self.consumed.add(k)
            v = super().__getitem__(k)
            super().__delitem__(k)
            return v
        return default


# Buffers transformers serializes that carry no weights.  ANCHORED regexes
# (suffix / dotted-boundary), not bare substrings: strict mode's loud-failure
# guarantee depends on these never over-matching a real weight key (a
# substring like ".attn.bias" would also swallow e.g. "cross_attn.bias_proj"
# from an unmapped architecture variant).
_IGNORABLE = tuple(
    re.compile(p)
    for p in (
        r"(^|\.)position_ids$",
        r"(^|\.)rotary_emb\.inv_freq$",
        r"(^|\.)attention\.self\.distance_embedding\.weight$",
        r"(^|\.)masked_bias$",
        r"(^|\.)attn\.bias$",  # gpt2's causal-mask buffer
        r"(^|\.)num_batches_tracked$",  # BN bookkeeping (momentum here is a constant)
    )
)


def import_state_dict(
    family: str,
    state_dict: dict,
    config,
    strict: bool = True,
    consume_source: bool = False,
) -> dict:
    """Map a transformers state dict onto the native param tree for
    ``family``, cast to ``config.param_dtype``.

    ``strict`` (default): raise if any checkpoint tensor was not consumed by
    the mapping — a dropped tensor means the converted model computes
    something different from the checkpoint.

    ``consume_source``: empty the caller's ``state_dict`` after copying the
    references in, so the read-releases in ``_RecordingDict`` actually free
    each source tensor as it is staged — peak host memory then stays ~one
    model copy.  Without it (e.g. ``from_hf``, where the torch module owns
    the tensors anyway) the deletions only shrink this function's view."""
    if family not in _IMPORTERS:
        raise ValueError(f"Unknown family {family!r}; supported: {sorted(_IMPORTERS)}")
    stripped = _strip_prefix(dict(state_dict), _PREFIXES[family])
    if consume_source:
        state_dict.clear()
    sd = _RecordingDict(stripped)
    del stripped
    params = _IMPORTERS[family](sd, config)
    if strict:
        leftover = [
            k for k in sd
            if k not in sd.consumed and not any(p.search(k) for p in _IGNORABLE)
        ]
        if leftover:
            raise ValueError(
                f"{family} import left {len(leftover)} checkpoint tensor(s) "
                f"unmapped (the converted model would silently diverge): "
                f"{sorted(leftover)[:8]}{'…' if len(leftover) > 8 else ''}. "
                "Pass strict=False to discard them knowingly."
            )
    dtype = config.param_dtype

    # Cast leaf-by-leaf IN PLACE so the fp32 staging tree and the target-dtype
    # tree never coexist in full (a 7B import would otherwise hold ~28 GB
    # fp32 next to the cast copy).  BN batch statistics (resnet) stay fp32 —
    # they are normalization state, not parameters.
    def cast_inplace(tree, leaf_dtype):
        for k, v in tree.items():
            if k == "batch_stats":
                cast_inplace(v, jnp.float32)
            elif isinstance(v, dict):
                cast_inplace(v, leaf_dtype)
            else:
                tree[k] = jnp.asarray(v, leaf_dtype)

    cast_inplace(params, dtype)
    return params


def load_hf_checkpoint(
    path: str, strict: bool = True, quantize: Optional[str] = None, **config_overrides
):
    """Load an HF checkpoint directory directly from disk ->
    ``(family, native_config, native_params)``.

    ``quantize="int8"`` applies the family's ``quantize_weights`` before
    returning (decoder families only) — one call from an HF directory to a
    >HBM-in-bf16 model decoding int8-weight-resident on a single chip.

    Reads ``config.json`` plus ``model.safetensors`` (or the
    ``model.safetensors.index.json`` shard index / legacy
    ``pytorch_model.bin``) without instantiating a torch module — at 7B+
    the torch model would double host memory for nothing.  Mirrors the
    reference's shard-streaming loader
    (``utils/modeling.py load_checkpoint_in_model``) for the native
    families."""
    import json
    import os

    with open(os.path.join(path, "config.json")) as f:
        raw = json.load(f)
    # config.json serializes id2label, not num_labels — derive it, or the
    # bert/vit classifier silently defaults to 2 labels.
    if "num_labels" not in raw and isinstance(raw.get("id2label"), dict):
        raw["num_labels"] = len(raw["id2label"])

    class _Cfg:
        def __init__(self, d):
            self.__dict__.update(d)

        def __getattr__(self, name):  # missing keys -> AttributeError
            raise AttributeError(name)

    hf_config = _Cfg(raw)
    family = _detect_family(hf_config)
    cfg = config_from_hf(hf_config, **config_overrides)

    # Validate the quantize request from config.json alone, BEFORE reading
    # shards — a typo'd mode or a family without the weight-resident path
    # must fail in milliseconds, not after tens of GB of IO.
    qw = None
    if quantize is not None:
        if quantize != "int8":
            raise ValueError(f"quantize must be 'int8' or None, got {quantize!r}")
        import importlib

        mod = importlib.import_module(f".{family}", __package__)
        qw = getattr(mod, "quantize_weights", None)
        if qw is None:
            raise ValueError(
                f"{family} has no int8-weight-resident path (quantize_weights)."
            )

    from ..checkpointing import read_safetensors_state_dict

    sd = read_safetensors_state_dict(path, "model.safetensors")
    if sd is None:
        legacy = os.path.join(path, "pytorch_model.bin")
        if os.path.exists(legacy):
            import torch

            sd = torch.load(legacy, map_location="cpu", weights_only=True)
        else:
            raise FileNotFoundError(
                f"No model.safetensors(.index.json) or pytorch_model.bin in {path}"
            )
    params = import_state_dict(family, sd, cfg, strict=strict, consume_source=True)
    if qw is not None:
        params = qw(params)
    return family, cfg, params


def from_hf(model, **config_overrides):
    """transformers model -> ``(family, native_config, native_params)``.

    >>> hf = transformers.AutoModelForCausalLM.from_pretrained(...)
    >>> family, cfg, params = from_hf(hf)
    >>> out = getattr(models, family).generate(params, ids, cfg, 64)
    """
    family = _detect_family(model.config)
    cfg = config_from_hf(model.config, **config_overrides)
    params = import_state_dict(family, model.state_dict(), cfg)
    return family, cfg, params
