"""Shared autoregressive generation driver for the model families.

Each family supplies ``init_cache(config, batch, max_len)`` and
``apply_cached(params, ids, config, cache) -> (logits, cache)``; the driver
compiles prefill + a one-token ``lax.scan`` decode loop into a single XLA
program (no per-token Python dispatch — the TPU-native answer to the
reference's torch generation loop, BASELINE.md s/token tables)."""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

__all__ = [
    "generate_loop", "select_token", "make_kv_cache", "check_cache_room",
    "quantize_kv", "dequantize_kv", "pack_cache_for_scan",
    "unpack_cache_from_scan", "cache_write", "speculative_generate_loop",
    "speculative_verify_greedy",
    "make_paged_pool", "gather_block_view", "extract_token_rows",
    "scatter_token_rows", "paged_cache_write", "pack_paged_pool_for_scan",
    "unpack_paged_rows_from_scan", "demote_pool_blocks", "promote_pool_blocks",
]


def make_kv_cache(num_layers: int, batch_size: int, max_len: int,
                  num_kv_heads: int, head_dim: int, dtype,
                  quantized: bool = False) -> dict:
    """Zeroed stacked KV cache shared by every family: k/v
    ``[L, B, max_len, K, hd]`` plus the int32 write index.

    ``quantized=True`` stores int8 codes with a per-(slot, head) absmax
    scale — halves cache HBM vs bf16 (2x the feasible context/batch at
    decode) at ~0.4% RMS quantization error per row.  Net-new vs the
    reference (no KV-cache machinery upstream at all)."""
    shape = (num_layers, batch_size, max_len, num_kv_heads, head_dim)
    if quantized:
        scale_shape = shape[:-1]
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(scale_shape, jnp.bfloat16),
            "v": jnp.zeros(shape, jnp.int8),
            "v_scale": jnp.zeros(scale_shape, jnp.bfloat16),
            "index": jnp.zeros((), jnp.int32),
        }
    return {
        "k": jnp.zeros(shape, dtype),
        "v": jnp.zeros(shape, dtype),
        "index": jnp.zeros((), jnp.int32),
    }


def quantize_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-(slot, head) absmax int8 quantization of new K/V rows:
    ``[..., hd]`` -> (codes int8 ``[..., hd]``, scale bf16 ``[...]``)."""
    scale = jnp.maximum(jnp.max(jnp.abs(x), axis=-1), 1e-6) / 127.0
    codes = jnp.clip(jnp.round(x / scale[..., None]), -127, 127).astype(jnp.int8)
    return codes, scale.astype(jnp.bfloat16)


def dequantize_kv(codes: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    """Inverse of :func:`quantize_kv`; the elementwise multiply fuses into
    the consuming attention matmul (no materialized fp cache)."""
    return codes.astype(dtype) * scale[..., None].astype(dtype)


def pack_cache_for_scan(cache: dict):
    """K/V leaves in the form a family's decode ``lax.scan`` threads: plain
    arrays, or (codes, scale) tuples for the int8 cache."""
    quant = "k_scale" in cache
    ck = (cache["k"], cache["k_scale"]) if quant else cache["k"]
    cv = (cache["v"], cache["v_scale"]) if quant else cache["v"]
    return ck, cv, quant


def unpack_cache_from_scan(new_k, new_v, index, quant: bool) -> dict:
    """Inverse of :func:`pack_cache_for_scan` for the scanned-out leaves."""
    if quant:
        return {
            "k": new_k[0], "k_scale": new_k[1],
            "v": new_v[0], "v_scale": new_v[1],
            "index": index,
        }
    return {"k": new_k, "v": new_v, "index": index}


def cache_write(cache_leaf, new_rows: jax.Array, index, dtype):
    """Write ``new_rows`` ``[B, S, K, hd]`` at ``index``; returns
    (updated leaf(s), full-precision view for attention).  Handles both the
    plain and int8 (codes, scale) layouts — shared by every family's cached
    attention."""
    if isinstance(cache_leaf, tuple):
        codes, scale = cache_leaf
        n_codes, n_scale = quantize_kv(new_rows)
        codes = jax.lax.dynamic_update_slice(codes, n_codes, (0, index, 0, 0))
        scale = jax.lax.dynamic_update_slice(scale, n_scale, (0, index, 0))
        return (codes, scale), dequantize_kv(codes, scale, dtype)
    updated = jax.lax.dynamic_update_slice(
        cache_leaf, new_rows.astype(cache_leaf.dtype), (0, index, 0, 0)
    )
    return updated, updated


# ---------------------------------------------------------------------------
# Paged (block) KV cache primitives — the storage layer under the serving
# engine (serving/engine.py).  The resident cache between decode steps is a
# POOL of fixed-size blocks shared by every request ([L, num_blocks,
# block_size, ...] per leaf) plus per-request block tables; these helpers
# translate between that pool and the dense per-request [L, B=1, T, ...]
# view the families' ``apply_cached`` already consumes, so paged serving
# needs no per-family changes.
# ---------------------------------------------------------------------------


def make_paged_pool(init_cache: Callable, config, num_blocks: int, block_size: int) -> dict:
    """Zeroed block pool derived from a family's own ``init_cache``: every
    non-``index`` leaf ``[L, 1, block_size, *rest]`` of the batch-1 template
    becomes ``[L, num_blocks, block_size, *rest]`` (so the int8 codes+scale
    layout pages exactly like the fp one).  Block 0 is the engine's reserved
    NULL block — table padding and inactive-slot writes route there, and no
    allocated region ever reads it."""
    template = init_cache(config, 1, block_size)
    pool = {}
    for name, leaf in template.items():
        if name == "index":
            continue
        if leaf.ndim < 3 or leaf.shape[1] != 1 or leaf.shape[2] != block_size:
            raise ValueError(
                f"cache leaf {name!r} has shape {leaf.shape}; paged serving needs "
                f"the make_kv_cache layout [L, B, max_len, ...] (batch axis 1, "
                f"token axis 2)"
            )
        pool[name] = jnp.zeros(
            (leaf.shape[0], num_blocks) + leaf.shape[2:], leaf.dtype
        )
    if not pool:
        raise ValueError("init_cache produced no pageable KV leaves")
    return pool


def gather_block_view(pool_leaf: jax.Array, tables: jax.Array) -> jax.Array:
    """Dense per-slot view of a pool leaf: ``[L, N, bs, *r]`` gathered through
    block tables ``[S, M]`` -> ``[S, L, 1, M*bs, *r]`` (the families'
    batch-1 cache layout, slot axis leading for ``vmap``).  Table entries
    pointing at the null block contribute rows that the causal mask hides —
    the engine keeps every real token position inside the allocated block
    prefix."""
    g = jnp.take(pool_leaf, tables, axis=1)  # [L, S, M, bs, *r]
    g = jnp.moveaxis(g, 1, 0)  # [S, L, M, bs, *r]
    s, l, m, bs = g.shape[:4]
    return g.reshape(s, l, 1, m * bs, *g.shape[4:])


def _token_positions(start: jax.Array, count: int) -> jax.Array:
    return start[:, None].astype(jnp.int32) + jnp.arange(count, dtype=jnp.int32)[None, :]


def extract_token_rows(view_leaf: jax.Array, start: jax.Array, count: int) -> jax.Array:
    """Pull the rows a forward pass just wrote out of the dense view:
    ``[S, L, 1, T, *r]`` at token positions ``start[s] + arange(count)`` ->
    ``[S, L, count, *r]``."""
    pos = _token_positions(start, count)  # [S, count]
    idx = pos.reshape(pos.shape[0], 1, 1, count, *([1] * (view_leaf.ndim - 4)))
    rows = jnp.take_along_axis(view_leaf, idx, axis=3)  # [S, L, 1, count, *r]
    return rows.reshape(rows.shape[0], rows.shape[1], count, *rows.shape[4:])


def scatter_token_rows(
    pool_leaf: jax.Array,
    rows: jax.Array,
    tables: jax.Array,
    start: jax.Array,
    count: int,
) -> jax.Array:
    """Write token rows ``[S, L, count, *r]`` back into the pool at positions
    ``start[s] + arange(count)`` through block tables ``[S, M]``.  Positions
    past the table extent (chunked-prefill padding) are routed to the null
    block explicitly — ``take_along_axis`` would otherwise CLAMP the block
    index and corrupt a real block."""
    bs = pool_leaf.shape[2]
    m = tables.shape[1]
    pos = _token_positions(start, count)  # [S, count]
    blk_idx = pos // bs
    blk = jnp.take_along_axis(tables, jnp.clip(blk_idx, 0, m - 1), axis=1)
    blk = jnp.where(blk_idx < m, blk, 0)
    off = pos % bs
    return pool_leaf.at[:, blk, off].set(jnp.moveaxis(rows, 0, 1))


def demote_pool_blocks(pool: dict, blocks) -> dict:
    """Gather whole blocks out of every pool leaf and land them in host
    memory: ``{name: [L, n, bs, *r] numpy}`` for ``n = len(blocks)``.  One
    device gather + one D2H transfer per leaf — the KV-tiering demotion
    primitive (serving/blocks.py), batched per call and never part of the
    fused decode dispatch.  On TPU the destination is the pinned-host
    mirror pool; ``device_get`` rather than a cross-memory-kind
    ``device_put`` keeps the copy a real transfer on CPU backends too,
    where host is already the default memory kind."""
    import numpy as np

    idx = jnp.asarray(blocks, jnp.int32)
    gathered = {name: jnp.take(leaf, idx, axis=1) for name, leaf in pool.items()}
    return {name: np.asarray(jax.device_get(g)) for name, g in gathered.items()}


def promote_pool_blocks(pool: dict, host_rows: dict, dst_blocks) -> dict:
    """Scatter host-resident block rows ``{name: [L, n, bs, *r]}`` back into
    the pool at block ids ``dst_blocks``; returns the updated pool.  One H2D
    transfer + one scatter per leaf — the promotion primitive paired with
    :func:`demote_pool_blocks`."""
    dst = jnp.asarray(dst_blocks, jnp.int32)
    return {
        name: leaf.at[:, dst].set(jnp.asarray(host_rows[name], leaf.dtype))
        for name, leaf in pool.items()
    }


def _insert_rows(ctx: jax.Array, new_rows: jax.Array, starts: jax.Array) -> jax.Array:
    """Overlay ``new_rows`` ``[B, T, *r]`` onto the gathered context ``[B, P,
    *r]`` at positions ``starts[b] .. starts[b]+T-1`` — the paged analog of
    the dense view after ``cache_write``: attention sees exactly the values a
    dense-view write would have produced, without an updated view ever being
    materialized as a program output."""
    b, p = ctx.shape[:2]
    t = new_rows.shape[1]
    rel = (
        jnp.arange(p, dtype=jnp.int32)[None, :]
        - starts[:, None].astype(jnp.int32)
    )  # [B, P]: position minus the slot's write start
    tail = (1,) * (ctx.ndim - 2)
    picked = jnp.take_along_axis(
        new_rows, jnp.clip(rel, 0, t - 1).reshape(b, p, *tail), axis=1
    )
    in_new = ((rel >= 0) & (rel < t)).reshape(b, p, *tail)
    return jnp.where(in_new, picked, ctx)


def paged_cache_write(pool_layer, new_rows: jax.Array, tables: jax.Array, starts: jax.Array, dtype):
    """Per-layer paged analog of :func:`cache_write`: compute the stored
    representation of ``new_rows`` ``[B, T, K, hd]`` (cast for the fp pool,
    ``(codes, scale)`` for the int8 one) and the **dense attention context**
    ``[B, M*bs, K, hd]`` gathered straight through the block tables ``[B, M]``
    with the new rows overlaid at ``starts[b] + arange(T)``.

    Unlike the dense path, nothing here flows back out as an updated cache:
    the pool leaf is consumed read-only (a scan ``xs``), the stored rows ride
    out as tiny per-layer ``ys``, and the engine scatters them into the
    donated pool after the forward — HBM write traffic per token is the new
    rows, not the per-slot worst-case view."""
    b = tables.shape[0]
    m = tables.shape[1]
    if isinstance(pool_layer, tuple):  # int8: (codes [N, bs, K, hd], scale [N, bs, K])
        codes, scale = pool_layer
        bs = codes.shape[1]
        n_codes, n_scale = quantize_kv(new_rows)
        stored = (n_codes, n_scale)
        ctx = dequantize_kv(
            jnp.take(codes, tables, axis=0).reshape(b, m * bs, *codes.shape[2:]),
            jnp.take(scale, tables, axis=0).reshape(b, m * bs, *scale.shape[2:]),
            dtype,
        )
        # Attention must see the QUANTIZED new rows (the dense path writes
        # codes then dequantizes the whole view) or int8 serving would not be
        # token-identical to the offline int8 cache.
        new_full = dequantize_kv(n_codes, n_scale, dtype)
    else:
        bs = pool_layer.shape[1]
        stored = new_rows.astype(pool_layer.dtype)
        ctx = jnp.take(pool_layer, tables, axis=0).reshape(
            b, m * bs, *pool_layer.shape[2:]
        )
        new_full = stored
    return stored, _insert_rows(ctx, new_full, starts)


def pack_paged_pool_for_scan(pool: dict):
    """Pool leaves in the tuple form the per-layer scan body consumes:
    ``(k, v)`` arrays, or ``((k, k_scale), (v, v_scale))`` for int8 — each
    leading with the layer axis so ``lax.scan`` slices one layer per step."""
    quant = "k_scale" in pool
    pk = (pool["k"], pool["k_scale"]) if quant else pool["k"]
    pv = (pool["v"], pool["v_scale"]) if quant else pool["v"]
    return pk, pv, quant


def unpack_paged_rows_from_scan(k_rows, v_rows, quant: bool) -> dict:
    """Stacked per-layer stored rows ``[L, B, T, ...]`` (scan ``ys``) ->
    ``{leaf: [B, L, T, ...]}``, the layout ``scatter_token_rows`` writes."""
    def out(rows):
        return jnp.moveaxis(rows, 0, 1)

    if quant:
        return {
            "k": out(k_rows[0]), "k_scale": out(k_rows[1]),
            "v": out(v_rows[0]), "v_scale": out(v_rows[1]),
        }
    return {"k": out(k_rows), "v": out(v_rows)}


def check_cache_room(index, new_tokens: int, max_len: int) -> None:
    """Eager-mode overflow guard: ``dynamic_update_slice`` CLAMPS an
    out-of-range write start under jit (silent cache corruption), so callers
    driving ``apply_cached`` directly get a real error when the index is
    concrete; traced callers rely on the documented ``index + S <= max_len``
    contract (generate_loop maintains it)."""
    try:
        concrete = int(index)
    except jax.errors.TracerIntegerConversionError:  # traced inside jit
        return
    except jax.errors.ConcretizationTypeError:  # abstract value (e.g. eval_shape)
        return
    if concrete + new_tokens > max_len:
        raise ValueError(
            f"KV cache overflow: index {concrete} + {new_tokens} new tokens > max_len {max_len}"
        )


def select_token(
    logits: jax.Array,
    temperature: float,
    key,
    i,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jax.Array:
    """Greedy argmax (temperature<=0) or filtered categorical sample at step
    ``i``.  ``top_k > 0`` keeps only the k highest logits; ``top_p < 1`` keeps
    the smallest set of tokens whose cumulative probability reaches p (the
    top-1 token is always kept).  Both are static, jit-friendly filters
    (sort + mask — no dynamic shapes)."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    sorted_desc = None  # shared by the two filters — at most ONE vocab sort
    if top_k > 0:
        k = min(int(top_k), logits.shape[-1])
        # Partial selection; the descending top-k values double as the sorted
        # prefix for the top_p pass (masked-out tokens carry zero probability,
        # so the softmax over the k survivors equals the full masked softmax).
        sorted_desc = jax.lax.top_k(logits, k)[0]
        logits = jnp.where(logits < sorted_desc[..., -1:], -jnp.inf, logits)
    if top_p < 1.0:
        if sorted_desc is None:
            sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # A token is cut when the mass BEFORE it already reaches p (so the
        # token that crosses the threshold is kept, and top-1 always is).
        cut = (cum - probs) >= top_p
        cutoff = jnp.min(jnp.where(cut, jnp.inf, sorted_desc), axis=-1, keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    step_key = jax.random.fold_in(key, i)
    return jax.random.categorical(step_key, logits, axis=-1).astype(jnp.int32)


def generate_loop(
    apply_cached: Callable,
    init_cache: Callable,
    params,
    input_ids: jax.Array,
    config,
    max_new_tokens: int,
    temperature: float = 0.0,
    key: Optional[jax.Array] = None,
    max_len: Optional[int] = None,
    top_k: int = 0,
    top_p: float = 1.0,
    prefill_chunk: Optional[int] = None,
) -> jax.Array:
    """Dense prompt ``[B, S]`` -> ``[B, S + max_new_tokens]``.

    ``prefill_chunk`` processes the prompt in slices of that many tokens:
    prefill attention scores are ``[B, chunk, max_len]`` instead of
    ``[B, S, max_len]``, which bounds prefill activation memory at long
    context (the decode loop is unaffected).  Identical outputs — the cache
    after chunked prefill equals the one-shot cache."""
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0, got {top_k}")
    if temperature <= 0.0 and (top_k > 0 or top_p < 1.0):
        raise ValueError(
            "top_k/top_p filter a SAMPLED distribution; greedy decoding "
            "(temperature<=0, the default) would silently ignore them — pass "
            "temperature>0 (with a PRNG key) to sample."
        )
    b, s = input_ids.shape
    total = s + max_new_tokens
    if max_len is None:
        max_len = total
    if total > max_len:
        raise ValueError(f"prompt ({s}) + max_new_tokens ({max_new_tokens}) > max_len ({max_len})")
    if temperature > 0 and key is None:
        raise ValueError("sampling (temperature > 0) needs a PRNG key")
    if max_new_tokens < 0:
        raise ValueError(f"max_new_tokens must be >= 0, got {max_new_tokens}")
    if max_new_tokens == 0:
        return input_ids

    cache = init_cache(config, b, max_len)
    if prefill_chunk is not None and prefill_chunk < 1:
        raise ValueError(f"prefill_chunk must be >= 1, got {prefill_chunk}")
    if prefill_chunk is None or prefill_chunk >= s:
        logits, cache = apply_cached(params, input_ids, config, cache)
    else:
        # Static chunk count: equal slices of prefill_chunk plus one tail
        # slice — at most two program shapes, no per-chunk retrace churn.
        for start in range(0, s, prefill_chunk):
            logits, cache = apply_cached(
                params, input_ids[:, start : start + prefill_chunk], config, cache
            )
    next_tok = select_token(logits[:, -1], temperature, key, 0, top_k=top_k, top_p=top_p)

    def step(carry, i):
        tok, cache, key = carry
        logits, cache = apply_cached(params, tok[:, None], config, cache)
        nxt = select_token(logits[:, -1], temperature, key, i, top_k=top_k, top_p=top_p)
        return (nxt, cache, key), tok

    (last, _, _), toks = jax.lax.scan(
        step, (next_tok, cache, key), jnp.arange(1, max_new_tokens)
    )
    generated = (
        jnp.concatenate([toks.T, last[:, None]], axis=1) if max_new_tokens > 1 else last[:, None]
    )
    return jnp.concatenate([input_ids, generated], axis=1)


def speculative_verify_greedy(
    t_logits: jax.Array,
    drafts: jax.Array,
    draft_len: Optional[jax.Array] = None,
) -> tuple[jax.Array, jax.Array]:
    """Per-row greedy verify/accept for draft-then-verify decoding — the
    accept kernel shared by the offline :func:`speculative_generate_loop`
    and the serving engine's in-dispatch verify (``serving/engine.py``).

    ``t_logits`` ``[B, γ+1, V]`` are the target's logits over the verify
    window (row ``j`` is the distribution AFTER consuming window token
    ``j``); ``drafts`` ``[B, γ]`` are the draft tokens fed at window
    positions ``1..γ``.  Returns ``(t, m)``: ``t`` ``[B, γ+1]`` the target
    argmax at every window position and ``m`` ``[B]`` the per-row accepted
    count — draft ``j`` is accepted iff it equals the target argmax at
    position ``j-1`` and every earlier draft was accepted.  The emitted
    chunk for row ``b`` is exactly ``t[b, :m[b]+1]``: accepted drafts equal
    the argmax rows they matched, and position ``m`` is the correction (on
    mismatch) or bonus (on full accept) token — which is what makes
    draft-then-verify token-identical to greedy decoding with the target
    alone.

    ``draft_len`` ``[B]`` (optional) masks per-row ragged proposals: draft
    positions at or beyond ``draft_len[b]`` can never be accepted.  This is
    the serving form — a static ``γ`` window carrying variable-length
    n-gram proposals per slot, mixed acceptance across rows in one dispatch.
    """
    gamma = drafts.shape[1]
    t = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)  # [B, γ+1]
    accept = t[:, :gamma] == drafts
    if draft_len is not None:
        accept = accept & (
            jnp.arange(gamma, dtype=jnp.int32)[None, :] < draft_len[:, None]
        )
    m = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)
    return t, m


def speculative_generate_loop(
    apply_cached: Callable,
    init_cache: Callable,
    params,
    config,
    draft_apply_cached: Callable,
    draft_init_cache: Callable,
    draft_params,
    draft_config,
    input_ids: jax.Array,
    max_new_tokens: int,
    num_draft_tokens: int = 4,
    max_len: Optional[int] = None,
    return_stats: bool = False,
    temperature: float = 0.0,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """Speculative decoding: a small draft model proposes ``γ =
    num_draft_tokens`` tokens autoregressively, the target verifies all of
    them (plus a bonus position) in ONE cached forward, and the longest
    accepted prefix lands — ``1..γ+1`` tokens per target forward instead
    of exactly 1.  Net-new vs the reference (no generation engine
    upstream); the TPU angle is that the whole propose→verify→accept round
    — including the variable-length accept — is one ``lax.while_loop``
    with static shapes, compiled once.

    Two modes, both distribution-exact w.r.t. the target alone:

    - ``temperature <= 0`` (default) — greedy: a draft token is accepted
      iff it equals the target's argmax; on mismatch the target's argmax
      is emitted.  Output **token-identical to greedy decoding with the
      target alone**.
    - ``temperature > 0`` (needs ``key``) — the Leviathan/Chen rejection
      scheme: draft token ``x`` (sampled from the draft's softmax ``q``)
      is accepted with probability ``min(1, p(x)/q(x))`` against the
      target's softmax ``p``; on rejection the replacement is sampled
      from the residual ``normalize(max(p - q, 0))``, and a full accept
      earns a bonus token sampled from ``p``.  Each emitted token is
      **exactly distributed as target-only sampling** at this
      temperature (the classic telescoping identity), so the speedup is
      again free of quality risk.

    Cache bookkeeping: both caches keep the invariant "``index`` counts the
    tokens strictly before ``last`` (the newest emitted, not-yet-fed
    token)".  Each round writes ``γ+1`` rows into both caches and then
    *rewinds* ``index`` to the accepted length; the next round's writes
    cover every stale row before any query can attend it (write extent
    ``[index', index'+γ]`` ⊇ stale ``[index', index+γ]`` since the accept
    count is ≥ 1), and the families' position-based causal mask hides
    anything beyond ``index``.

    This *offline loop* is batch-1 only: the dense bundled cache carries a
    single shared ``index``, so rows with different accept counts would
    need per-row cache indices.  That is a limitation of this loop's cache
    layout, **not** of speculative decoding — the serving engine runs the
    per-slot form (``ServingConfig.spec_tokens``) where paged block tables
    already carry per-slot lengths, so one fused dispatch verifies every
    slot's window with per-slot variable acceptance (the accept kernel,
    :func:`speculative_verify_greedy`, is shared with this loop).  ``top_k``
    / ``top_p`` are not supported here — filtering changes both
    distributions and the residual algebra; use ``generate_loop`` for
    filtered sampling.

    ``return_stats=True`` additionally returns ``{"rounds", "proposed",
    "accepted"}`` (int32 scalars): ``accepted / proposed`` is the draft
    acceptance rate — the quantity that decides the real-world speedup
    (``rounds`` target forwards produced ``accepted + rounds`` tokens).
    """
    b, s = input_ids.shape
    if b != 1:
        raise ValueError(
            f"speculative decoding is batch-1 only (got batch {b}): rows with "
            "different accept counts would need per-row cache indices"
        )
    sampled = temperature > 0.0
    if sampled and key is None:
        raise ValueError("sampled speculative decoding (temperature > 0) needs a PRNG key")
    gamma = int(num_draft_tokens)
    if gamma < 1:
        raise ValueError(f"num_draft_tokens must be >= 1, got {num_draft_tokens}")
    tv = getattr(config, "vocab_size", None)
    dv = getattr(draft_config, "vocab_size", None)
    if tv != dv:
        raise ValueError(f"target and draft vocab sizes differ: {tv} vs {dv}")
    if max_new_tokens < 0:
        raise ValueError(f"max_new_tokens must be >= 0, got {max_new_tokens}")
    if max_new_tokens == 0:
        return input_ids
    # The last round can start at generated-count max_new-1 and still write
    # γ+1 rows — the caches need that much slack past the final token.
    need = s + max_new_tokens + gamma
    if max_len is None:
        max_len = need
    elif max_len < need:
        raise ValueError(
            f"max_len ({max_len}) < prompt + max_new_tokens + num_draft_tokens "
            f"({need}): the verify writes need overshoot room"
        )

    t_cache = init_cache(config, b, max_len)
    d_cache = draft_init_cache(draft_config, b, max_len)
    t_logits, t_cache = apply_cached(params, input_ids, config, t_cache)
    _, d_cache = draft_apply_cached(draft_params, input_ids, draft_config, d_cache)
    if sampled:
        # fp32 before the divide: the PROPOSAL distribution and the p/q used
        # in acceptance must be computed from identical logits, or the
        # rejection identity (and the exactness claim) silently breaks on
        # bf16 models.
        first = jax.random.categorical(
            jax.random.fold_in(key, 0),
            t_logits[:, -1].astype(jnp.float32) / temperature,
            axis=-1,
        ).astype(jnp.int32)
    else:
        first = jnp.argmax(t_logits[:, -1], axis=-1).astype(jnp.int32)  # [B]

    buf = jnp.zeros((b, max_new_tokens + gamma + 1), jnp.int32)
    buf = jax.lax.dynamic_update_slice(buf, first[:, None], (0, 0))

    def cond(carry):
        return carry[0] < max_new_tokens

    def body(carry):
        n, last, t_cache, d_cache, buf, rounds, accepted = carry
        # Per-round key stream, derived from the static base key and the
        # round counter — deterministic, no key in the carry.
        rkey = jax.random.fold_in(key, 1 + rounds) if sampled else None

        # Draft proposes γ tokens — a one-token cached step under lax.scan
        # (cache in the carry), so the draft forward compiles ONCE however
        # large γ is.  One extra feed (logits discarded) keeps the draft
        # cache covering d_γ so a full accept stays aligned.
        def d_step(dcarry, j):
            dc, tok = dcarry
            dl, dc = draft_apply_cached(draft_params, tok[:, None], draft_config, dc)
            logits = dl[:, -1].astype(jnp.float32)  # [B, V]; fp32 so q == the
            # distribution actually sampled (see the `first` comment)
            if sampled:
                nxt = jax.random.categorical(
                    jax.random.fold_in(rkey, j), logits / temperature, axis=-1
                ).astype(jnp.int32)
            else:
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (dc, nxt), (nxt, logits)

        (dc, tok), (d_steps, d_logits) = jax.lax.scan(
            d_step, (d_cache, last), jnp.arange(gamma)
        )
        _, dc = draft_apply_cached(draft_params, tok[:, None], draft_config, dc)
        d = jnp.moveaxis(d_steps, 0, 1)  # [γ, B] -> [B, γ]

        # Target verifies [last, d_1..d_γ] in one forward: row j carries the
        # target's distribution AFTER consuming seq[:, j].
        seq = jnp.concatenate([last[:, None], d], axis=1)  # [B, γ+1]
        t_logits, tc = apply_cached(params, seq, config, t_cache)

        if sampled:
            # Rejection acceptance: keep d_j with prob min(1, p(d_j)/q(d_j)).
            p = jax.nn.softmax(t_logits.astype(jnp.float32) / temperature, axis=-1)
            q = jax.nn.softmax(
                jnp.moveaxis(d_logits, 0, 1).astype(jnp.float32) / temperature, axis=-1
            )  # [B, γ, V]
            p_head = p[:, :gamma]
            p_at_d = jnp.take_along_axis(p_head, d[..., None], axis=-1)[..., 0]
            q_at_d = jnp.take_along_axis(q, d[..., None], axis=-1)[..., 0]
            u = jax.random.uniform(jax.random.fold_in(rkey, gamma), (b, gamma))
            accept = (u * jnp.maximum(q_at_d, 1e-30) < p_at_d).astype(jnp.int32)
            m = jnp.sum(jnp.cumprod(accept, axis=1), axis=1)[0]  # scalar; b == 1
            # Replacement at the stop position: residual normalize(max(p-q, 0))
            # on a rejection, plain p on a full accept (bonus token).  A ~zero
            # residual (p == q numerically) falls back to p — acceptance was
            # then certain, so the branch is all but unreachable anyway.
            resid = jnp.maximum(p_head - q, 0.0)
            mass = jnp.sum(resid, axis=-1, keepdims=True)
            resid = jnp.where(mass > 1e-9, resid, p_head)
            dist = jnp.concatenate([resid, p[:, gamma:]], axis=1)  # [B, γ+1, V]
            dist_m = jax.lax.dynamic_index_in_dim(dist, m, axis=1, keepdims=False)
            fill = jax.random.categorical(
                jax.random.fold_in(rkey, gamma + 1), jnp.log(dist_m + 1e-38), axis=-1
            ).astype(jnp.int32)  # [B]
            fill_col = jnp.broadcast_to(fill[:, None], (b, gamma + 1))
        else:
            # Greedy acceptance: d_j must equal the target argmax; the fill
            # column is the target argmax itself (correction or bonus).
            # Shared per-row kernel with the serving engine's in-dispatch
            # verify — see speculative_verify_greedy.
            t, m_rows = speculative_verify_greedy(t_logits, d)
            m = m_rows[0]  # scalar; b == 1
            fill_col = t

        # The accepted chunk is [d_1..d_m, fill] — count = m+1, uniformly.
        count = m + 1
        d_pad = jnp.concatenate([d, jnp.zeros((b, 1), jnp.int32)], axis=1)
        chunk = jnp.where(jnp.arange(gamma + 1)[None, :] < m, d_pad, fill_col)
        buf = jax.lax.dynamic_update_slice(buf, chunk, (0, n))
        last = jax.lax.dynamic_index_in_dim(chunk, m, axis=1, keepdims=False)
        # Rewind both caches to the accepted length (both wrote γ+1 rows).
        tc = {**tc, "index": tc["index"] - (gamma + 1) + count}
        dc = {**dc, "index": dc["index"] - (gamma + 1) + count}
        return n + count, last, tc, dc, buf, rounds + 1, accepted + m

    zero = jnp.asarray(0, jnp.int32)
    carry = (jnp.asarray(1, jnp.int32), first, t_cache, d_cache, buf, zero, zero)
    _, _, _, _, buf, rounds, accepted = jax.lax.while_loop(cond, body, carry)
    out = jnp.concatenate([input_ids, buf[:, :max_new_tokens]], axis=1)
    if return_stats:
        return out, {"rounds": rounds, "proposed": rounds * gamma, "accepted": accepted}
    return out


def beam_search(
    apply_cached: Callable,
    init_cache: Callable,
    params,
    input_ids: jax.Array,
    config,
    max_new_tokens: int,
    num_beams: int = 4,
    length_penalty: float = 1.0,
    eos_token_id: Optional[int] = None,
    max_len: Optional[int] = None,
) -> jax.Array:
    """Beam search over the shared KV cache — one compiled XLA program.

    Dense prompt ``[B, S]`` -> best sequence ``[B, S + max_new_tokens]``.
    Each step scores ``num_beams * vocab`` continuations, keeps the top
    ``num_beams``, and reorders the cache rows to follow their beams (the
    same reorder torch generation does, here a ``jnp.take`` inside the scan).
    Beams that emit ``eos_token_id`` freeze: their score stops accumulating
    and they pad with EOS.  Final ranking divides by ``length**length_penalty``
    (>1 favors longer sequences, <1 shorter).

    Cache contract: every cache leaf with ``ndim >= 2`` MUST carry the batch
    on **axis 1** (the bundled families' ``[L, B, max_len, K, hd]`` layout from
    :func:`make_kv_cache` does).  Beam tiling/reordering identifies
    batch-bearing leaves by ``leaf.shape[1] == batch`` (then ``== batch*K``
    inside the scan); a custom ``init_cache`` whose batch lives on another
    axis — or a non-batch leaf whose axis-1 size coincides with the batch —
    is silently mis-tiled.  Scalar/1-D leaves (e.g. the write index) are
    left untouched.
    """
    if max_new_tokens < 1:
        raise ValueError("beam search needs max_new_tokens >= 1")
    if num_beams < 1:
        raise ValueError(f"num_beams must be >= 1, got {num_beams}")
    b, s = input_ids.shape
    kbeams = num_beams
    total = s + max_new_tokens
    if max_len is None:
        max_len = total
    if total > max_len:
        raise ValueError(f"prompt ({s}) + max_new_tokens ({max_new_tokens}) > max_len ({max_len})")

    # Prefill ONCE at batch B (all beams share the prompt — tiling the prompt
    # would multiply prefill FLOPs/HBM by K), then tile the cache rows per beam.
    cache = init_cache(config, b, max_len)
    logits, cache = apply_cached(params, input_ids, config, cache)
    cache = jax.tree.map(
        lambda leaf: jnp.repeat(leaf, kbeams, axis=1)
        if leaf.ndim >= 2 and leaf.shape[1] == b
        else leaf,
        cache,
    )
    logp = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32), axis=-1)  # [B, V]
    vocab = logp.shape[-1]
    if kbeams > vocab:
        raise ValueError(
            f"num_beams ({kbeams}) > vocab_size ({vocab}): top_k cannot select "
            "more beams than there are tokens"
        )

    # First expansion: the top-K tokens of the single (shared) beam.
    scores, tokens = jax.lax.top_k(logp, kbeams)  # [B, K]
    tokens = tokens.astype(jnp.int32)
    finished = (
        tokens == eos_token_id if eos_token_id is not None else jnp.zeros_like(tokens, bool)
    )
    lengths = jnp.ones((b, kbeams), jnp.int32)

    out = jnp.zeros((b, kbeams, max_new_tokens), jnp.int32)
    out = out.at[:, :, 0].set(tokens)

    batch_offsets = (jnp.arange(b) * kbeams)[:, None]  # [B, 1]

    def step(carry, i):
        tokens, scores, finished, lengths, out, cache = carry
        logits, new_cache = apply_cached(
            params, tokens.reshape(b * kbeams, 1), config, cache
        )
        logp = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32), axis=-1)
        logp = logp.reshape(b, kbeams, vocab)
        if eos_token_id is not None:
            # Frozen beams only continue with EOS at zero added score.
            frozen = jnp.full((vocab,), -jnp.inf).at[eos_token_id].set(0.0)
            logp = jnp.where(finished[:, :, None], frozen[None, None, :], logp)
        cand = (scores[:, :, None] + logp).reshape(b, kbeams * vocab)
        new_scores, flat_idx = jax.lax.top_k(cand, kbeams)
        beam_idx = (flat_idx // vocab).astype(jnp.int32)  # [B, K] source beam
        new_tokens = (flat_idx % vocab).astype(jnp.int32)

        gather_rows = (batch_offsets + beam_idx).reshape(-1)  # [B*K] cache rows

        def reorder(leaf):
            if leaf.ndim >= 2 and leaf.shape[1] == b * kbeams:
                return jnp.take(leaf, gather_rows, axis=1)
            return leaf

        cache = jax.tree.map(reorder, new_cache)
        out = jnp.take_along_axis(out, beam_idx[:, :, None], axis=1)
        out = out.at[:, :, i].set(new_tokens)
        prev_finished = jnp.take_along_axis(finished, beam_idx, axis=1)
        lengths = jnp.take_along_axis(lengths, beam_idx, axis=1) + (~prev_finished)
        if eos_token_id is not None:
            finished = prev_finished | (new_tokens == eos_token_id)
        else:
            finished = prev_finished
        return (new_tokens, new_scores, finished, lengths, out, cache), None

    if max_new_tokens > 1:
        (tokens, scores, finished, lengths, out, cache), _ = jax.lax.scan(
            step,
            (tokens, scores, finished, lengths, out, cache),
            jnp.arange(1, max_new_tokens),
        )

    ranked = scores / (lengths.astype(jnp.float32) ** length_penalty)
    best = jnp.argmax(ranked, axis=1)  # [B]
    best_out = jnp.take_along_axis(out, best[:, None, None], axis=1)[:, 0]  # [B, max_new]
    return jnp.concatenate([input_ids, best_out], axis=1)
