"""Shared autoregressive generation driver for the model families.

Each family supplies ``init_cache(config, batch, max_len)`` and
``apply_cached(params, ids, config, cache) -> (logits, cache)``; the driver
compiles prefill + a one-token ``lax.scan`` decode loop into a single XLA
program (no per-token Python dispatch — the TPU-native answer to the
reference's torch generation loop, BASELINE.md s/token tables)."""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

__all__ = ["generate_loop", "select_token", "make_kv_cache", "check_cache_room"]


def make_kv_cache(num_layers: int, batch_size: int, max_len: int,
                  num_kv_heads: int, head_dim: int, dtype) -> dict:
    """Zeroed stacked KV cache shared by every family: k/v
    ``[L, B, max_len, K, hd]`` plus the int32 write index."""
    shape = (num_layers, batch_size, max_len, num_kv_heads, head_dim)
    return {
        "k": jnp.zeros(shape, dtype),
        "v": jnp.zeros(shape, dtype),
        "index": jnp.zeros((), jnp.int32),
    }


def check_cache_room(index, new_tokens: int, max_len: int) -> None:
    """Eager-mode overflow guard: ``dynamic_update_slice`` CLAMPS an
    out-of-range write start under jit (silent cache corruption), so callers
    driving ``apply_cached`` directly get a real error when the index is
    concrete; traced callers rely on the documented ``index + S <= max_len``
    contract (generate_loop maintains it)."""
    try:
        concrete = int(index)
    except jax.errors.TracerIntegerConversionError:  # traced inside jit
        return
    except jax.errors.ConcretizationTypeError:  # abstract value (e.g. eval_shape)
        return
    if concrete + new_tokens > max_len:
        raise ValueError(
            f"KV cache overflow: index {concrete} + {new_tokens} new tokens > max_len {max_len}"
        )


def select_token(logits: jax.Array, temperature: float, key, i) -> jax.Array:
    """Greedy argmax (temperature<=0) or categorical sample at step ``i``."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    step_key = jax.random.fold_in(key, i)
    return jax.random.categorical(step_key, logits / temperature, axis=-1).astype(jnp.int32)


def generate_loop(
    apply_cached: Callable,
    init_cache: Callable,
    params,
    input_ids: jax.Array,
    config,
    max_new_tokens: int,
    temperature: float = 0.0,
    key: Optional[jax.Array] = None,
    max_len: Optional[int] = None,
) -> jax.Array:
    """Dense prompt ``[B, S]`` -> ``[B, S + max_new_tokens]``."""
    b, s = input_ids.shape
    total = s + max_new_tokens
    if max_len is None:
        max_len = total
    if total > max_len:
        raise ValueError(f"prompt ({s}) + max_new_tokens ({max_new_tokens}) > max_len ({max_len})")
    if temperature > 0 and key is None:
        raise ValueError("sampling (temperature > 0) needs a PRNG key")
    if max_new_tokens < 0:
        raise ValueError(f"max_new_tokens must be >= 0, got {max_new_tokens}")
    if max_new_tokens == 0:
        return input_ids

    cache = init_cache(config, b, max_len)
    logits, cache = apply_cached(params, input_ids, config, cache)
    next_tok = select_token(logits[:, -1], temperature, key, 0)

    def step(carry, i):
        tok, cache, key = carry
        logits, cache = apply_cached(params, tok[:, None], config, cache)
        nxt = select_token(logits[:, -1], temperature, key, i)
        return (nxt, cache, key), tok

    (last, _, _), toks = jax.lax.scan(
        step, (next_tok, cache, key), jnp.arange(1, max_new_tokens)
    )
    generated = (
        jnp.concatenate([toks.T, last[:, None]], axis=1) if max_new_tokens > 1 else last[:, None]
    )
    return jnp.concatenate([input_ids, generated], axis=1)
