"""Flagship model: Llama-style decoder, designed TPU-first.

No reference analog (the reference wraps user torch models); this is the model used
by our benchmarks (BASELINE.md: Llama-3-8B FSDP on v5e) and the graft entry.

TPU-first choices:
- Parameters are a flat pytree of stacked per-layer arrays so the decoder runs as a
  single ``lax.scan`` over layers — one compiled layer body, fast compiles, and
  clean pipeline-parallel stage splitting later.
- bf16 compute / fp32 params + fp32 softmax & loss (MXU-friendly, stable).
- Every weight carries a `PartitionSpec` (``PARTITION_RULES``) over the named mesh
  axes (fsdp/tp/sp); activations get ``with_sharding_constraint`` at layer
  boundaries so GSPMD keeps batch on data axes and sequence on ``sp``.
- GQA + RoPE, RMSNorm, SwiGLU — the Llama-3 architecture family.
- Optional ``jax.checkpoint`` rematerialization of each layer (HBM for FLOPs).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = [
    "LlamaConfig",
    "init_params",
    "apply",
    "loss_fn",
    "labels_and_weights",
    "cross_entropy",
    "PARTITION_RULES",
    "param_specs",
]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: Optional[int] = None
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    rms_eps: float = 1e-5
    tie_embeddings: bool = False
    # Q/K/V projection biases (the Qwen2-class variant of the llama
    # architecture; plain llama keeps False).
    attention_bias: bool = False
    # Gemma-class conventions: GeGLU MLP ("gelu_tanh"), (1 + w) RMSNorm
    # scales (stored weights start at zero), sqrt(d)-scaled embeddings.
    hidden_act: str = "silu"  # "silu" | "gelu_tanh"
    rms_offset: bool = False
    embed_scale: bool = False
    # Llama-3.1 long-context RoPE rescaling: ("llama3", factor,
    # low_freq_factor, high_freq_factor, original_max_position_embeddings)
    # as a hashable tuple (None = plain RoPE).
    rope_scaling: Optional[tuple] = None
    dtype: Any = jnp.bfloat16  # compute dtype
    param_dtype: Any = jnp.float32
    remat: bool = True
    # "nothing": recompute the whole layer in backward (lowest memory).
    # "dots": save matmul outputs, recompute elementwise only — needs flash
    # attention (scores never materialize) to fit, and removes most of the
    # remat FLOPs tax.
    remat_policy: str = "nothing"
    # "einsum": materialize scores (fast at short seq, supports padding masks).
    # "flash": blockwise online-softmax (ops/flash_attention.py).
    # "pallas": fused Pallas MXU kernel (ops/pallas_attention.py); on a
    #   sharded (non-sp) mesh it runs per-device under shard_map
    #   (pallas_attention_spmd) since pallas_call is opaque to GSPMD.
    # "auto": pallas on TPU (single chip, or a non-sp mesh whose batch/head
    #   shapes divide the data/tp axes), else flash for long sequences
    #   without padding masks.
    attention_impl: str = "auto"
    # Sequence-parallel attention implementation when the mesh has sp > 1:
    # "ring" rotates K/V via neighbor ppermute (works for any head count);
    # "ulysses" re-shards seq->heads with one all-to-all each way (needs
    # num_heads % sp == 0; cheaper when the torus all-to-all is fast).
    sp_impl: str = "ring"
    # fp8 matmuls (ops/fp8.py scaled_matmul): projection/MLP weights quantized
    # per-tensor to e4m3 with fp32 accumulation; embed/unembed stay in `dtype`
    # (the reference's fp8 bridges likewise skip first/last layers,
    # utils/ao.py:104).
    fp8: bool = False
    # int8 KV cache for generation: codes + per-slot absmax scales — half the
    # cache HBM (2x feasible context/batch at decode), ~0.4% RMS per-row
    # quantization error.
    kv_cache_quant: bool = False
    # "dense": logits [B,S,V] materialize in fp32 (fastest at tiny vocab).
    # "chunked": ops/chunked_ce.py streams the head matmul over vocab tiles
    #   with an online logsumexp — peak HBM drops by the full logits tensor
    #   (+ its cotangent), the binding constraint on batch size at real vocab.
    loss_impl: str = "dense"
    loss_chunk_size: int = 4096

    def __post_init__(self):
        if self.rope_scaling is not None and (
            not isinstance(self.rope_scaling, tuple)
            or len(self.rope_scaling) != 5
            or self.rope_scaling[0] != "llama3"
        ):
            raise ValueError(
                "rope_scaling must be None or ('llama3', factor, "
                f"low_freq_factor, high_freq_factor, original_max), got "
                f"{self.rope_scaling!r}"
            )
        if self.hidden_act not in ("silu", "gelu_tanh"):
            raise ValueError(
                f"hidden_act must be 'silu' or 'gelu_tanh', got {self.hidden_act!r}"
            )
        if self.attention_impl not in ("auto", "einsum", "flash", "pallas"):
            raise ValueError(
                "attention_impl must be 'auto', 'einsum', 'flash' or 'pallas', "
                f"got {self.attention_impl!r}"
            )
        if self.remat_policy not in ("nothing", "dots"):
            raise ValueError(f"remat_policy must be 'nothing' or 'dots', got {self.remat_policy!r}")
        if self.sp_impl not in ("ring", "ulysses"):
            raise ValueError(f"sp_impl must be 'ring' or 'ulysses', got {self.sp_impl!r}")
        if self.loss_impl not in ("dense", "chunked"):
            raise ValueError(f"loss_impl must be 'dense' or 'chunked', got {self.loss_impl!r}")

    @property
    def head_dim_(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        """Test-sized config (CPU-mesh friendly)."""
        defaults = dict(
            vocab_size=256,
            hidden_size=64,
            intermediate_size=128,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            max_seq_len=128,
            remat=False,
        )
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def llama3_8b(cls, **kw) -> "LlamaConfig":
        defaults = dict(
            vocab_size=128256,
            hidden_size=4096,
            intermediate_size=14336,
            num_layers=32,
            num_heads=32,
            num_kv_heads=8,
        )
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def llama3_70b(cls, **kw) -> "LlamaConfig":
        defaults = dict(
            vocab_size=128256,
            hidden_size=8192,
            intermediate_size=28672,
            num_layers=80,
            num_heads=64,
            num_kv_heads=8,
        )
        defaults.update(kw)
        return cls(**defaults)

    def flops_per_token(self) -> float:
        """Approximate training FLOPs per token (6 * params for matmuls + attention
        quadratic term is handled by callers with seq length)."""
        return 6.0 * self.num_params()

    def num_params(self) -> int:
        d, f, v, l = self.hidden_size, self.intermediate_size, self.vocab_size, self.num_layers
        hd = self.head_dim_
        attn = d * self.num_heads * hd + 2 * d * self.num_kv_heads * hd + self.num_heads * hd * d
        mlp = 3 * d * f
        norms = 2 * d
        embed = v * d * (1 if self.tie_embeddings else 2)
        return l * (attn + mlp + norms) + embed + d


# Mesh-axis layout of every parameter (path regex -> PartitionSpec).  Matmul
# weights shard their contraction-free dim on `tp` and the other on `fsdp`
# (Megatron layout expressed as GSPMD annotations; XLA inserts the all-gathers/
# reduce-scatters the reference delegated to torch FSDP/Megatron).
PARTITION_RULES: list[tuple[str, P]] = [
    (r"embed", P("tp", "fsdp")),
    (r"layers/wq", P(None, "fsdp", "tp")),
    (r"layers/wk", P(None, "fsdp", "tp")),
    (r"layers/wv", P(None, "fsdp", "tp")),
    (r"layers/wo", P(None, "tp", "fsdp")),
    (r"layers/w_gate", P(None, "fsdp", "tp")),
    (r"layers/w_up", P(None, "fsdp", "tp")),
    (r"layers/w_down", P(None, "tp", "fsdp")),
    (r"layers/b[qkv]$", P(None, "tp")),
    (r"layers/bo$", P(None, "fsdp")),
    (r"layers/ln_", P(None, None)),
    (r"final_norm", P(None)),
    (r"lm_head", P("fsdp", "tp")),
]


def param_specs(config: LlamaConfig) -> dict:
    """Pytree of PartitionSpecs matching ``init_params``' structure."""
    from ..parallel.sharding import spec_from_rules

    shapes = _param_shapes(config)

    def one(kp, shape):
        path = "/".join(str(getattr(k, "key", k)) for k in kp)
        spec = spec_from_rules(path, len(shape), PARTITION_RULES)
        return spec if spec is not None else P(*([None] * len(shape)))

    return jax.tree_util.tree_map_with_path(
        one, shapes, is_leaf=lambda x: isinstance(x, tuple)
    )


def _param_shapes(config: LlamaConfig) -> dict:
    c = config
    d, f, hd = c.hidden_size, c.intermediate_size, c.head_dim_
    L = c.num_layers
    shapes = {
        "embed": (c.vocab_size, d),
        "layers": {
            "wq": (L, d, c.num_heads * hd),
            "wk": (L, d, c.num_kv_heads * hd),
            "wv": (L, d, c.num_kv_heads * hd),
            "wo": (L, c.num_heads * hd, d),
            "w_gate": (L, d, f),
            "w_up": (L, d, f),
            "w_down": (L, f, d),
            "ln_attn": (L, d),
            "ln_mlp": (L, d),
        },
        "final_norm": (d,),
    }
    if c.attention_bias:
        shapes["layers"]["bq"] = (L, c.num_heads * hd)
        shapes["layers"]["bk"] = (L, c.num_kv_heads * hd)
        shapes["layers"]["bv"] = (L, c.num_kv_heads * hd)
        shapes["layers"]["bo"] = (L, d)  # zero in qwen2 (no o_proj bias)
    if not c.tie_embeddings:
        shapes["lm_head"] = (d, c.vocab_size)
    return shapes


def init_params(config: LlamaConfig, key: jax.Array) -> dict:
    """Initialize parameters (truncated-normal fan-in scaling)."""
    shapes = _param_shapes(config)
    leaves, treedef = jax.tree_util.tree_flatten(shapes, is_leaf=lambda x: isinstance(x, tuple))
    keys = jax.tree_util.tree_unflatten(treedef, list(jax.random.split(key, len(leaves))))

    def init_one(kp, shape, k):
        # Dispatch on the param NAME, not shape — a shape test would turn the
        # (vocab, d) embedding into ones whenever vocab == num_layers.
        name = str(getattr(kp[-1], "key", kp[-1]))
        if name in ("ln_attn", "ln_mlp", "final_norm"):
            # Offset convention stores scales as (w - 1): start at zero.
            fill = jnp.zeros if config.rms_offset else jnp.ones
            return fill(shape, config.param_dtype)  # norm scales
        if name in ("bq", "bk", "bv", "bo"):
            return jnp.zeros(shape, config.param_dtype)  # attention biases
        # Embedding table: lookup is one-hot (effective fan-in 1), so scale by
        # hidden size, not vocab size.
        fan_in = config.hidden_size if name == "embed" else shape[-2]
        scale = 1.0 / np.sqrt(fan_in)
        return (jax.random.truncated_normal(k, -2.0, 2.0, shape, jnp.float32) * scale).astype(
            config.param_dtype
        )

    return jax.tree_util.tree_map_with_path(
        init_one, shapes, keys, is_leaf=lambda x: isinstance(x, tuple)
    )


from ..parallel.sharding import (  # noqa: E402
    _abstract_mesh,
    constrain as _maybe_constrain,
    embed_lookup as _embed_lookup,
)


def _sp_active() -> bool:
    """True when the installed global mesh has a >1 sequence-parallel axis."""
    m = _abstract_mesh()
    return bool(m is not None and not m.empty and "sp" in m.axis_names and m.shape["sp"] > 1)


def _sp_use_pallas(c, s: int, head_dim: int) -> bool:
    """Pallas selection for the sequence-parallel paths: explicit opt-in
    always (the kernel auto-interprets off-TPU); "auto" on TPU when the
    per-device sequence chunk still tiles into VMEM blocks.  Configs without
    the knob (bert/gpt2) default to "auto"."""
    impl = getattr(c, "attention_impl", "auto")
    if impl == "pallas":
        return True
    if impl != "auto":
        return False
    try:
        from ..ops.flash_attention import pick_block_pallas
        from ..ops.pallas_attention import pallas_available
    except ImportError:  # pragma: no cover
        return False
    if not pallas_available() or jax.default_backend() != "tpu":
        return False
    m = _abstract_mesh()
    sp = m.shape["sp"] if m is not None and "sp" in m.axis_names else 1
    return s % sp == 0 and pick_block_pallas(s // sp, head_dim=head_dim) is not None


def _rms_norm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    # fp32 statistics regardless of compute dtype.
    x32 = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * rms).astype(x.dtype) * scale.astype(x.dtype)


def _norm(x: jax.Array, scale: jax.Array, c) -> jax.Array:
    """Config-dispatched RMSNorm: gemma's (1 + w) scale convention when
    ``rms_offset`` (weights stored as offsets from one, multiplied in fp32
    before the downcast — matching transformers' GemmaRMSNorm); the plain
    llama/mixtral scale otherwise."""
    if getattr(c, "rms_offset", False):
        x32 = x.astype(jnp.float32)
        rms = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + c.rms_eps)
        return (x32 * rms * (1.0 + scale.astype(jnp.float32))).astype(x.dtype)
    return _rms_norm(x, scale, c.rms_eps)


def _act(x: jax.Array, c) -> jax.Array:
    """Gate activation: SwiGLU's silu, or gemma's tanh-approximate GeLU."""
    if getattr(c, "hidden_act", "silu") == "gelu_tanh":
        return jax.nn.gelu(x, approximate=True)
    return jax.nn.silu(x)


def _rope_freqs(hd: int, theta: float, scaling) -> jax.Array:
    """Inverse frequencies, with the llama-3.1 long-context rescaling when
    ``scaling`` is ``("llama3", factor, low_freq_factor, high_freq_factor,
    original_max_position_embeddings)``: wavelengths longer than
    original/low_freq are divided by ``factor``, shorter than
    original/high_freq are kept, and the band between interpolates smoothly
    (the transformers ``_compute_llama3_parameters`` rule)."""
    freqs = 1.0 / (theta ** (jnp.arange(0, hd, 2, dtype=jnp.float32) / hd))
    if scaling is None:
        return freqs
    kind, factor, low_f, high_f, orig = scaling
    if kind != "llama3":  # validated at config build; defensive here
        raise ValueError(f"unsupported rope_scaling type {kind!r}")
    wavelen = 2.0 * np.pi / freqs
    low_wavelen = orig / low_f
    high_wavelen = orig / high_f
    scaled = freqs / factor
    smooth = (orig / wavelen - low_f) / (high_f - low_f)
    smoothed = (1.0 - smooth) * scaled + smooth * freqs
    out = jnp.where(wavelen > low_wavelen, scaled, freqs)
    mid = (wavelen <= low_wavelen) & (wavelen >= high_wavelen)
    return jnp.where(mid, smoothed, out)


def _rope(q: jax.Array, k: jax.Array, positions: jax.Array, theta: float,
          scaling=None) -> tuple[jax.Array, jax.Array]:
    """Rotary embeddings applied to [B, S, H, hd] queries/keys."""
    hd = q.shape[-1]
    freqs = _rope_freqs(hd, theta, scaling)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, S, hd/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]

    def rot(x):
        x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
        return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1).astype(x.dtype)

    return rot(q), rot(k)


def _attention(q, k, v, mask, num_groups: int):
    """Causal GQA attention.  [B, S, H, hd] x [B, S, K, hd].

    Round-1 implementation is plain einsum+softmax (XLA fuses well on the MXU);
    the Pallas splash/ring kernel plugs in here for long-context (`ops/`).
    """
    b, s, h, hd = q.shape
    kk = k.shape[2]
    q = q.reshape(b, s, kk, num_groups, hd)
    scores = jnp.einsum("bskgd,btkd->bkgst", q, k).astype(jnp.float32)
    scores = scores / np.sqrt(hd)
    scores = jnp.where(mask[:, None, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgst,btkd->bskgd", probs, v)
    return out.reshape(b, s, h, hd)


def _flash_block(s: int):
    """Largest MXU-friendly block dividing ``s`` (None -> einsum fallback);
    short sequences (<= 1024) run as one block."""
    from ..ops.flash_attention import pick_block

    return pick_block(s, max_single_block=1024)


def _use_pallas(c: "LlamaConfig", s: int, b: int, h: int, kh: int) -> bool:
    """Pick the fused Pallas kernel.  Explicit opt-in always; "auto" on TPU
    when single-device, or on a multi-device non-sp mesh whose batch/head
    shapes divide the data/tp axes (the spmd shard_map wrapper then runs the
    kernel per-device; sp>1 needs ring/ulysses instead)."""
    if c.attention_impl == "pallas":
        return True
    if c.attention_impl != "auto" or s < 1024 or _flash_block(s) is None:
        return False
    try:
        from ..ops.pallas_attention import pallas_available
    except ImportError:
        return False
    if not pallas_available() or jax.default_backend() != "tpu":
        return False
    if jax.device_count() == 1:
        return True
    from ..state import AcceleratorState

    if not AcceleratorState._shared_state:
        return False
    mesh = AcceleratorState().mesh
    if mesh is None or ("sp" in mesh.axis_names and mesh.shape["sp"] > 1):
        return False
    from ..ops.ring_attention import tp_head_axis
    from ..parallel.mesh import data_axes

    n_batch_shards = 1
    for a in data_axes(mesh):
        n_batch_shards *= mesh.shape[a]
    tp = mesh.shape.get("tp", 1)
    head_ok = tp == 1 or tp_head_axis(mesh, h, kh) is not None
    return b % n_batch_shards == 0 and head_ok


def _mm(h: jax.Array, w: jax.Array, c: LlamaConfig) -> jax.Array:
    """Projection matmul honoring the precision mode: ``config.fp8`` or an
    active ``fp8_autowrap`` context (mixed_precision="fp8") routes through the
    scaled float8 matmul."""
    from ..ops import fp8 as _fp8

    recipe = _fp8.active_recipe()
    if c.fp8 or recipe is not None:
        fwd, grad = _fp8.recipe_dtypes(recipe)
        return _fp8.scaled_matmul(h, w, dtype=fwd, grad_dtype=grad, out_dtype=c.dtype)
    return h @ w.astype(c.dtype)


def sp_attention(q, k, v, c, *, causal: bool = True, kv_valid=None) -> jax.Array:
    """Shared sequence-parallel attention dispatch over the ``sp`` axis —
    q ``[B, S, H, hd]``, k/v ``[B, S, K, hd]`` sequence-sharded; the
    key-validity vector rides the ring / all-gathers in the ulysses body.
    One implementation for every family (llama/mixtral/gpt2/bert), including
    the fused-Pallas fast paths (per-block inside the ppermute ring;
    per-device local attention in ulysses), selected by the same policy as
    the dense path minus the padded-batch case the kernel does not mask.
    ``c`` needs ``sp_impl``/``attention_impl`` (getattr defaults cover
    configs without the knobs)."""
    s = q.shape[1]
    sp_pallas = _sp_use_pallas(c, s, q.shape[-1])
    if getattr(c, "sp_impl", "ring") == "ulysses":
        from ..ops.ulysses_attention import ulysses_attention

        return ulysses_attention(
            q, k, v, mesh=None, axis_name="sp", causal=causal, kv_valid=kv_valid,
            impl="pallas" if sp_pallas else None,
        )
    if sp_pallas and kv_valid is None:
        # The pallas RING variant has no validity plumbing (the chunks would
        # have to ride the ring); padded ring batches take the einsum path.
        from ..ops.pallas_attention import ring_attention_pallas

        return ring_attention_pallas(q, k, v, mesh=None, axis_name="sp", causal=causal)
    from ..ops.ring_attention import ring_attention

    return ring_attention(q, k, v, mesh=None, axis_name="sp", causal=causal, kv_valid=kv_valid)


def _qkv_proj(h, p, c, b: int, s: int):
    """Q/K/V projections with the optional Qwen2-style biases (present in
    ``p`` iff ``attention_bias`` — key presence is static at trace time, so
    the plain-llama path compiles without the adds)."""
    hd = c.head_dim_
    q = _mm(h, p["wq"], c)
    k = _mm(h, p["wk"], c)
    v = _mm(h, p["wv"], c)
    if "bq" in p:
        q = q + p["bq"].astype(q.dtype)
        k = k + p["bk"].astype(k.dtype)
        v = v + p["bv"].astype(v.dtype)
    return (
        q.reshape(b, s, c.num_heads, hd),
        k.reshape(b, s, c.num_kv_heads, hd),
        v.reshape(b, s, c.num_kv_heads, hd),
    )


def attention_block(x, p, c, mask, positions, kv_valid=None) -> jax.Array:
    """Pre-norm attention sub-block with residual: shared by llama and the MoE
    models (mixtral) — both get the ring-attention (sp) and fp8 paths from one
    implementation.

    ``mask`` is a full [B, S, S] mask for callers with non-causal patterns;
    ``kv_valid`` [B, S] is the padding mask for causal batches — kept factored
    so the flash/ring/ulysses paths never materialize an [S, S] mask.
    """
    hd = c.head_dim_
    h = _norm(x, p["ln_attn"], c)
    b, s, _ = h.shape
    q, k, v = _qkv_proj(h, p, c, b, s)
    q, k = _rope(q, k, positions, c.rope_theta, getattr(c, 'rope_scaling', None))
    if _sp_active():
        attn = sp_attention(q, k, v, c, causal=True, kv_valid=kv_valid)
    elif mask is None and _use_pallas(c, s, b, c.num_heads, c.num_kv_heads):
        from ..ops.pallas_attention import pallas_attention_spmd

        from ..ops.flash_attention import pick_block_pallas

        blk = pick_block_pallas(s, head_dim=q.shape[-1])
        if blk is None:
            raise ValueError(
                f"attention_impl='pallas' needs a sequence length divisible by "
                f"64/128/256/512 (VMEM tiling); got seq_len={s}"
            )
        # On a sharded (non-sp) mesh the spmd wrapper runs the kernel
        # per-device under shard_map; trivial meshes take the plain call.
        # Padded batches mask keys inside the kernel (round 5).
        attn = pallas_attention_spmd(q, k, v, causal=True, block_size=blk, kv_valid=kv_valid)
    elif mask is None and (
        c.attention_impl == "flash" or (c.attention_impl == "auto" and s >= 1024)
    ) and _flash_block(s) is not None:
        from ..ops.flash_attention import flash_attention

        attn = flash_attention(
            q, k, v, causal=True, block_size=_flash_block(s), kv_valid=kv_valid
        )
    else:
        if mask is None:
            mask = jnp.broadcast_to(jnp.tril(jnp.ones((s, s), bool)), (b, s, s))
            if kv_valid is not None:
                mask = mask & kv_valid.astype(bool)[:, None, :]
        attn = _attention(q, k, v, mask, c.num_heads // c.num_kv_heads)
    out = _mm(attn.reshape(b, s, c.num_heads * hd), p["wo"], c)
    if "bo" in p:
        out = out + p["bo"].astype(out.dtype)
    return x + out


def _layer(carry, layer_params, *, config: LlamaConfig, mask, positions, act_spec, kv_valid=None):
    c = config
    p = layer_params
    x = attention_block(carry, p, c, mask, positions, kv_valid=kv_valid)

    h = _norm(x, p["ln_mlp"], c)
    gate = _act(_mm(h, p["w_gate"], c), c)
    up = _mm(h, p["w_up"], c)
    x = x + _mm(gate * up, p["w_down"], c)
    if act_spec is not None:
        x = _maybe_constrain(x, act_spec)
    return x, None


def _dequant_layer(lp):
    """Per-layer int8-weight hook: dequantize QuantizedArray leaves of a
    scanned layer slice (see ``quantize_weights``); no-op on plain params."""
    from ..utils.quantization import dequantize_layer_slice

    return dequantize_layer_slice(lp)


def quantize_weights(params: dict, block_size: int = 64) -> dict:
    """int8-weight-resident storage: blockwise-quantize the stacked decoder
    layers (embed / final_norm / lm_head and the per-layer norm scales stay
    full precision).  The result drops HBM weight bytes ~2x and feeds every
    ``apply*``/``generate*`` path unchanged — the scan bodies dequantize each
    layer slice as it is consumed, which XLA fuses into the consuming
    matmuls.  This is the single-chip answer for models whose bf16 weights
    exceed HBM (reference frame: disk/cpu-offloaded big-model inference,
    ``benchmarks/big_model_inference``)."""
    from ..utils.quantization import quantize_layer_stack

    out = dict(params)
    out["layers"] = quantize_layer_stack(params["layers"], block_size)
    return out


def apply(
    params: dict,
    input_ids: jax.Array,
    config: LlamaConfig,
    positions: Optional[jax.Array] = None,
    attention_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Forward pass: token ids [B, S] -> logits [B, S, V] (fp32)."""
    hidden = apply_hidden(params, input_ids, config, positions, attention_mask)
    return (hidden @ lm_head(params, config)).astype(jnp.float32)


def apply_hidden(
    params: dict,
    input_ids: jax.Array,
    config: LlamaConfig,
    positions: Optional[jax.Array] = None,
    attention_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Trunk forward: token ids [B, S] -> final-normed hidden [B, S, d]
    (compute dtype) — the chunked loss consumes this directly so the full
    logits tensor never exists."""
    c = config
    b, s = input_ids.shape
    # Padding stays factored as a [B, S] key-validity vector all the way down —
    # every attention path (flash blocks, ring chunks, ulysses all-gather,
    # einsum) applies it without materializing a [B, S, S] mask here.
    kv_valid = attention_mask.astype(bool) if attention_mask is not None else None
    if positions is None:
        if kv_valid is not None:
            # Upstream-stack semantics: positions count real tokens, so
            # left-padded prompts get correct RoPE offsets.
            positions = jnp.maximum(jnp.cumsum(kv_valid.astype(jnp.int32), axis=-1) - 1, 0)
        else:
            positions = jnp.broadcast_to(jnp.arange(s), (b, s))

    x = embed_tokens(params, input_ids, c)
    act_spec = P(("dcn_dp", "dp", "fsdp"), "sp", None)
    x = _maybe_constrain(x, act_spec)

    def body(carry, lp):
        return _layer(
            carry, _dequant_layer(lp), config=c, mask=None, positions=positions,
            act_spec=act_spec, kv_valid=kv_valid,
        )

    if c.remat:
        body = jax.checkpoint(body, policy=_remat_policy(c.remat_policy))
    x, _ = jax.lax.scan(body, x, params["layers"])
    return final_norm(params, x, c)


def _remat_policy(name: str):
    if name == "nothing":
        return jax.checkpoint_policies.nothing_saveable
    if name == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    raise ValueError(f"Unknown remat_policy {name!r} (use 'nothing' or 'dots')")


def embed_tokens(params: dict, input_ids: jax.Array, config: LlamaConfig) -> jax.Array:
    """Token embedding lookup in compute dtype — shared by the dense and
    pipeline-parallel paths.  ``embed_scale`` multiplies by sqrt(d) in the
    compute dtype (gemma convention: the normalizer is cast to the hidden
    dtype before the multiply)."""
    x = _embed_lookup(params["embed"], input_ids, config.dtype)
    if config.embed_scale:
        x = x * jnp.asarray(config.hidden_size**0.5, config.dtype)
    return x


def final_norm(params: dict, x: jax.Array, config: LlamaConfig) -> jax.Array:
    """The pre-head RMS norm (shared by the dense and chunked loss paths)."""
    return _norm(x, params["final_norm"], config)


def lm_head(params: dict, config: LlamaConfig) -> jax.Array:
    """The [d, V] head matrix in compute dtype (transposed view when tied)."""
    head = params["embed"].T if config.tie_embeddings else params["lm_head"]
    return head.astype(config.dtype)


def unembed(params: dict, x: jax.Array, config: LlamaConfig) -> jax.Array:
    """Final norm + LM head -> fp32 logits — shared by the dense and
    pipeline-parallel paths."""
    return (final_norm(params, x, config) @ lm_head(params, config)).astype(jnp.float32)


def labels_and_weights(batch: dict) -> tuple[jax.Array, jax.Array]:
    """Next-token labels + fp32 loss weights from a batch dict.

    ``batch``: {"input_ids": [B, S]} (+ optional "labels", "attention_mask").
    """
    input_ids = batch["input_ids"]
    labels = batch.get("labels")
    if labels is None:
        labels = jnp.concatenate([input_ids[:, 1:], jnp.zeros_like(input_ids[:, :1])], axis=1)
        weights = jnp.concatenate(
            [jnp.ones_like(input_ids[:, 1:]), jnp.zeros_like(input_ids[:, :1])], axis=1
        ).astype(jnp.float32)
    else:
        weights = (labels >= 0).astype(jnp.float32)
        labels = jnp.maximum(labels, 0)
    if "attention_mask" in batch and batch["attention_mask"] is not None:
        weights = weights * batch["attention_mask"].astype(jnp.float32)
    return labels, weights


def cross_entropy(logits: jax.Array, labels: jax.Array, weights: jax.Array) -> jax.Array:
    """Weighted-mean token cross-entropy in fp32."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    token_loss = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return jnp.sum(token_loss * weights) / jnp.maximum(jnp.sum(weights), 1.0)


def loss_fn(
    params: dict,
    batch: dict,
    config: LlamaConfig,
) -> jax.Array:
    """Next-token cross-entropy, fp32, mean over non-padded targets.

    ``config.loss_impl == "chunked"`` computes the same loss through
    ``ops/chunked_ce.py`` without ever materializing the [B, S, V] logits —
    the HBM that usually caps the batch size."""
    labels, weights = labels_and_weights(batch)
    if config.loss_impl == "chunked":
        from ..ops.chunked_ce import chunked_cross_entropy

        x = apply_hidden(
            params, batch["input_ids"], config, attention_mask=batch.get("attention_mask")
        )
        return chunked_cross_entropy(
            x, lm_head(params, config), labels, weights, config.loss_chunk_size
        )
    logits = apply(params, batch["input_ids"], config, attention_mask=batch.get("attention_mask"))
    return cross_entropy(logits, labels, weights)


# ---------------------------------------------------------------------------
# KV-cache inference (prefill + decode)
# ---------------------------------------------------------------------------
#
# The reference's big-model inference path generates through torch/transformers
# (BASELINE.md s-per-token tables); the TPU-native equivalent is a compiled
# decode step over a static-shape KV cache: cache tensors are stacked per layer
# so prefill/decode run the same single lax.scan layer body as training, and
# the whole generate loop is one jit (no per-token Python dispatch).


def init_cache(config: LlamaConfig, batch_size: int, max_len: int) -> dict:
    """Zeroed KV cache: k/v ``[L, B, max_len, K, hd]`` + write index.
    ``config.kv_cache_quant`` stores int8 codes + per-slot scales (half the
    cache HBM)."""
    from .generation import make_kv_cache

    c = config
    return make_kv_cache(
        c.num_layers, batch_size, max_len, c.num_kv_heads, c.head_dim_, c.dtype,
        quantized=getattr(c, "kv_cache_quant", False),
    )


def _attention_block_cached(x, p, c, ck, cv, index, positions):
    """Attention sub-block against the cache.  x: [B, S, D] (S = new tokens);
    ck/cv: [B, max_len, K, hd].  Returns (out, new_ck, new_cv)."""
    hd = c.head_dim_
    h = _norm(x, p["ln_attn"], c)
    b, s, _ = h.shape
    max_len = (ck[0] if isinstance(ck, tuple) else ck).shape[1]
    q, k, v = _qkv_proj(h, p, c, b, s)
    q, k = _rope(q, k, positions, c.rope_theta, getattr(c, 'rope_scaling', None))

    from .generation import cache_write

    # Plain and int8 (codes, scale) cache layouts share one write/read
    # helper; the dequant multiply fuses into the attention matmuls.
    ck, k_full = cache_write(ck, k, index, c.dtype)
    cv, v_full = cache_write(cv, v, index, c.dtype)

    # q position i (global index + i) attends cache slots <= its position.
    q_pos = index + jnp.arange(s)
    k_pos = jnp.arange(max_len)
    mask = jnp.broadcast_to(q_pos[:, None] >= k_pos[None, :], (b, s, max_len))
    attn = _attention(q, k_full, v_full, mask, c.num_heads // c.num_kv_heads)
    out = _mm(attn.reshape(b, s, c.num_heads * hd), p["wo"], c)
    if "bo" in p:
        out = out + p["bo"].astype(out.dtype)
    return x + out, ck, cv


def apply_cached(
    params: dict,
    input_ids: jax.Array,
    config: LlamaConfig,
    cache: dict,
) -> tuple[jax.Array, dict]:
    """Forward over new tokens with cache read/write.

    input_ids ``[B, S]`` are the tokens at positions ``cache['index'] ..
    index+S``; returns (logits ``[B, S, V]``, updated cache)."""
    from .generation import check_cache_room

    c = config
    b, s = input_ids.shape
    index = cache["index"]
    check_cache_room(index, s, cache["k"].shape[2])
    positions = jnp.broadcast_to(index + jnp.arange(s), (b, s))
    x = embed_tokens(params, input_ids, c)

    from .generation import pack_cache_for_scan, unpack_cache_from_scan

    def body(carry, xs):
        lp, ck, cv = xs
        lp = _dequant_layer(lp)
        y, ck, cv = _attention_block_cached(carry, lp, c, ck, cv, index, positions)
        h = _norm(y, lp["ln_mlp"], c)
        gate = _act(_mm(h, lp["w_gate"], c), c)
        up = _mm(h, lp["w_up"], c)
        return y + _mm(gate * up, lp["w_down"], c), (ck, cv)

    ck_in, cv_in, quant = pack_cache_for_scan(cache)
    x, (new_k, new_v) = jax.lax.scan(body, x, (params["layers"], ck_in, cv_in))
    logits = unembed(params, x, c)
    return logits, unpack_cache_from_scan(new_k, new_v, index + s, quant)


def apply_paged(
    params: dict,
    input_ids: jax.Array,
    config: LlamaConfig,
    pool: dict,
    tables: jax.Array,
    starts: jax.Array,
    kernel: bool = False,
) -> tuple[jax.Array, dict]:
    """Forward over new tokens straight against the paged block pool — the
    serving engine's decode/prefill fast path (see ``gpt2.apply_paged``; the
    contract is shared).  Row ``b``'s tokens sit at positions ``starts[b] ..
    starts[b]+T-1`` (RoPE is position-exact per slot); attention consumes
    pool K/V through the block tables via ``paged_cache_write`` and the
    written rows return as ``{leaf: [B, L, T, ...]}`` for the caller's
    scatter.  ``kernel=True`` routes fp decode through the Pallas
    paged-attention kernels: single-token at ``T == 1``, the multi-token
    window variant at ``T > 1`` (the speculative verify dispatch; GQA folds
    into the kernel's grouped layout); int8 pools stay on the XLA path."""
    from .generation import (
        pack_paged_pool_for_scan,
        paged_cache_write,
        unpack_paged_rows_from_scan,
    )

    c = config
    b, t = input_ids.shape
    hd = c.head_dim_
    _, _, quant = pack_paged_pool_for_scan(pool)
    bs = pool["k"].shape[2]
    total = tables.shape[1] * bs
    positions = starts[:, None].astype(jnp.int32) + jnp.arange(t, dtype=jnp.int32)[None]
    x = embed_tokens(params, input_ids, c)
    k_pos = jnp.arange(total, dtype=jnp.int32)
    mask = positions[:, :, None] >= k_pos[None, None, :]  # [B, T, M*bs]
    use_kernel = kernel and not quant
    if use_kernel:
        from ..ops.pallas_attention import pallas_available

        use_kernel = pallas_available()

    def body(carry, xs):
        if quant:
            lp, ck, cks, cv, cvs = xs
            pk, pv = (ck, cks), (cv, cvs)
        else:
            lp, pk, pv = xs
        lp = _dequant_layer(lp)
        x = carry
        h = _norm(x, lp["ln_attn"], c)
        q, k, v = _qkv_proj(h, lp, c, b, t)
        q, k = _rope(q, k, positions, c.rope_theta, getattr(c, "rope_scaling", None))
        if use_kernel:
            from ..ops.pallas_attention import (
                pallas_paged_attention,
                pallas_paged_window_attention,
            )

            k_store = k.astype(pk.dtype)
            v_store = v.astype(pv.dtype)
            if t == 1:
                attn = pallas_paged_attention(
                    q[:, 0], k_store[:, 0], v_store[:, 0], pk, pv, tables, starts
                )[:, None]
            else:
                attn = pallas_paged_window_attention(
                    q, k_store, v_store, pk, pv, tables, starts
                )
        else:
            k_store, k_full = paged_cache_write(pk, k, tables, starts, c.dtype)
            v_store, v_full = paged_cache_write(pv, v, tables, starts, c.dtype)
            attn = _attention(q, k_full, v_full, mask, c.num_heads // c.num_kv_heads)
        out = _mm(attn.reshape(b, t, c.num_heads * hd), lp["wo"], c)
        if "bo" in lp:
            out = out + lp["bo"].astype(out.dtype)
        y = x + out
        h = _norm(y, lp["ln_mlp"], c)
        gate = _act(_mm(h, lp["w_gate"], c), c)
        up = _mm(h, lp["w_up"], c)
        return y + _mm(gate * up, lp["w_down"], c), (k_store, v_store)

    xs = (params["layers"],) + (
        (pool["k"], pool["k_scale"], pool["v"], pool["v_scale"]) if quant
        else (pool["k"], pool["v"])
    )
    x, (k_rows, v_rows) = jax.lax.scan(body, x, xs)
    logits = unembed(params, x, c)
    return logits, unpack_paged_rows_from_scan(k_rows, v_rows, quant)


def generate(
    params: dict,
    input_ids: jax.Array,
    config: LlamaConfig,
    max_new_tokens: int,
    temperature: float = 0.0,
    key: Optional[jax.Array] = None,
    max_len: Optional[int] = None,
    top_k: int = 0,
    top_p: float = 1.0,
    prefill_chunk: Optional[int] = None,
) -> jax.Array:
    """Greedy (temperature=0) or sampled autoregressive generation.

    input_ids ``[B, S]`` dense prompt (no padding) -> ``[B, S+max_new_tokens]``.
    The decode loop is a single ``lax.scan`` of a one-token cached step, so the
    whole call compiles to one XLA program.
    """
    from .generation import generate_loop

    return generate_loop(
        apply_cached, init_cache, params, input_ids, config,
        max_new_tokens, temperature=temperature, key=key, max_len=max_len,
        top_k=top_k, top_p=top_p, prefill_chunk=prefill_chunk,
    )


def speculative_generate(
    params: dict,
    draft_params: dict,
    input_ids: jax.Array,
    config: LlamaConfig,
    draft_config: LlamaConfig,
    max_new_tokens: int,
    num_draft_tokens: int = 4,
    max_len: Optional[int] = None,
    return_stats: bool = False,
    temperature: float = 0.0,
    key=None,
) -> jax.Array:
    """Speculative decoding with a small draft llama — up to
    ``num_draft_tokens + 1`` tokens per target forward.  ``temperature<=0``
    (default): output token-identical to ``generate(..., temperature=0)``;
    ``temperature>0`` (pass ``key``): rejection-sampling mode,
    distribution-exact w.r.t. target-only sampling (see
    ``models/generation.py speculative_generate_loop``).  Batch 1 only."""
    from .generation import speculative_generate_loop

    return speculative_generate_loop(
        apply_cached, init_cache, params, config,
        apply_cached, init_cache, draft_params, draft_config,
        input_ids, max_new_tokens,
        num_draft_tokens=num_draft_tokens, max_len=max_len,
        return_stats=return_stats, temperature=temperature, key=key,
    )


def generate_beam(
    params: dict,
    input_ids: jax.Array,
    config: LlamaConfig,
    max_new_tokens: int,
    num_beams: int = 4,
    length_penalty: float = 1.0,
    eos_token_id: Optional[int] = None,
    max_len: Optional[int] = None,
) -> jax.Array:
    """Beam-search generation (see ``models/generation.py beam_search``)."""
    from .generation import beam_search

    return beam_search(
        apply_cached, init_cache, params, input_ids, config, max_new_tokens,
        num_beams=num_beams, length_penalty=length_penalty,
        eos_token_id=eos_token_id, max_len=max_len,
    )
