"""T5-style encoder-decoder — third architecture family, TPU-first.

Parity rationale: the reference's Megatron bridge ships ``T5TrainStep``
(``utils/megatron_lm.py:719``); this native family covers the encoder-decoder
class: relative position bias (no absolute/rotary embeddings), RMSNorm without
bias, ReLU MLP, cross-attention, tied embeddings scaled at the head.

Same TPU-first layout as the other families: stacked per-layer params under
``lax.scan``, bf16 compute / fp32 params, partition rules over the mesh.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.sharding import constrain as _constrain, embed_lookup as _embed_lookup
from .llama import _dequant_layer, _rms_norm

__all__ = ["T5Config", "init_params", "apply", "loss_fn", "PARTITION_RULES", "param_specs"]


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    hidden_size: int = 512
    intermediate_size: int = 2048
    num_layers: int = 6  # per stack (encoder and decoder)
    num_heads: int = 8
    head_dim: int = 64
    num_buckets: int = 32
    max_distance: int = 128
    rms_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False
    # "chunked" streams the (tied, 1/sqrt(d)-scaled) LM-head loss over vocab
    # tiles (ops/chunked_ce.py) — same knob as LlamaConfig.loss_impl.
    # int8 self-attn KV cache for decoding (shared machinery; see
    # LlamaConfig).  Cross K/V stay full precision.
    kv_cache_quant: bool = False
    loss_impl: str = "dense"
    loss_chunk_size: int = 4096

    def __post_init__(self):
        if self.loss_impl not in ("dense", "chunked"):
            raise ValueError(f"loss_impl must be 'dense' or 'chunked', got {self.loss_impl!r}")

    @classmethod
    def tiny(cls, **kw) -> "T5Config":
        defaults = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                        num_layers=2, num_heads=4, head_dim=16, num_buckets=8, max_distance=32)
        defaults.update(kw)
        return cls(**defaults)


PARTITION_RULES: list[tuple[str, P]] = [
    (r"shared_embed", P("tp", "fsdp")),
    (r"/(wq|wk|wv|cross_wq|cross_wk|cross_wv)", P(None, "fsdp", "tp")),
    (r"/(wo|cross_wo)", P(None, "tp", "fsdp")),
    (r"/w_up", P(None, "fsdp", "tp")),
    (r"/w_down", P(None, "tp", "fsdp")),
    (r"rel_bias", P(None, None)),
    (r"final_ln", P(None)),
    (r"/ln_", P(None, None)),
]


def _stack_shapes(c: T5Config, decoder: bool) -> dict:
    d, f, L, hd = c.hidden_size, c.intermediate_size, c.num_layers, c.head_dim
    h = c.num_heads
    shapes = {
        "wq": (L, d, h * hd),
        "wk": (L, d, h * hd),
        "wv": (L, d, h * hd),
        "wo": (L, h * hd, d),
        "w_up": (L, d, f),
        "w_down": (L, f, d),
        "ln_attn": (L, d),
        "ln_mlp": (L, d),
    }
    if decoder:
        shapes.update(
            {
                "cross_wq": (L, d, h * hd),
                "cross_wk": (L, d, h * hd),
                "cross_wv": (L, d, h * hd),
                "cross_wo": (L, h * hd, d),
                "ln_cross": (L, d),
            }
        )
    return shapes


def _param_shapes(c: T5Config) -> dict:
    return {
        "shared_embed": (c.vocab_size, c.hidden_size),
        "enc_rel_bias": (c.num_buckets, c.num_heads),
        "dec_rel_bias": (c.num_buckets, c.num_heads),
        "encoder": _stack_shapes(c, decoder=False),
        "decoder": _stack_shapes(c, decoder=True),
        "enc_final_ln": (c.hidden_size,),
        "dec_final_ln": (c.hidden_size,),
    }


def param_specs(config: T5Config) -> dict:
    from ..parallel.sharding import spec_from_rules

    shapes = _param_shapes(config)

    def one(kp, shape):
        path = "/".join(str(getattr(k, "key", k)) for k in kp)
        spec = spec_from_rules(path, len(shape), PARTITION_RULES)
        return spec if spec is not None else P(*([None] * len(shape)))

    return jax.tree_util.tree_map_with_path(one, shapes, is_leaf=lambda x: isinstance(x, tuple))


def init_params(config: T5Config, key: jax.Array) -> dict:
    shapes = _param_shapes(config)
    leaves, treedef = jax.tree_util.tree_flatten(shapes, is_leaf=lambda x: isinstance(x, tuple))
    keys = jax.tree_util.tree_unflatten(treedef, list(jax.random.split(key, len(leaves))))

    def init_one(kp, shape, k):
        # Name-based dispatch (see llama.init_params): shape tests misfire
        # when e.g. num_buckets == num_layers or vocab_size == num_layers.
        name = str(getattr(kp[-1], "key", kp[-1]))
        if name.startswith("ln_") or name.endswith("_final_ln"):
            return jnp.ones(shape, config.param_dtype)  # RMSNorm scales
        if name.endswith("_rel_bias"):
            return jnp.zeros(shape, config.param_dtype)  # relative bias starts flat
        fan_in = shape[-2] if len(shape) >= 2 else shape[0]
        return (jax.random.normal(k, shape, jnp.float32) / np.sqrt(fan_in)).astype(
            config.param_dtype
        )

    return jax.tree_util.tree_map_with_path(
        init_one, shapes, keys, is_leaf=lambda x: isinstance(x, tuple)
    )


def _relative_buckets(rel_pos: jax.Array, num_buckets: int, max_distance: int, bidirectional: bool):
    """T5 relative-position bucketing (log-spaced beyond the exact range)."""
    ret = jnp.zeros_like(rel_pos)
    n = -rel_pos
    if bidirectional:
        num_buckets //= 2
        ret = ret + (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    large = max_exact + (
        jnp.log(jnp.maximum(n, 1).astype(jnp.float32) / max_exact)
        / np.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    large = jnp.minimum(large, num_buckets - 1)
    return ret + jnp.where(is_small, n, large)


def _rel_bias(table: jax.Array, q_len: int, k_len: int, c: T5Config, bidirectional: bool):
    ctx = jnp.arange(q_len)[:, None]
    mem = jnp.arange(k_len)[None, :]
    buckets = _relative_buckets(mem - ctx, c.num_buckets, c.max_distance, bidirectional)
    return table[buckets].transpose(2, 0, 1)  # [H, q, k]


def _mha(h_q, h_kv, p, prefix, c: T5Config, bias, mask):
    b, sq, _ = h_q.shape
    sk = h_kv.shape[1]
    hd, nh = c.head_dim, c.num_heads
    q = (h_q @ p[prefix + "wq"].astype(c.dtype)).reshape(b, sq, nh, hd)
    k = (h_kv @ p[prefix + "wk"].astype(c.dtype)).reshape(b, sk, nh, hd)
    v = (h_kv @ p[prefix + "wv"].astype(c.dtype)).reshape(b, sk, nh, hd)
    scores = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32)  # T5: no 1/sqrt(d)
    if bias is not None:
        scores = scores + bias[None]
    if mask is not None:
        scores = jnp.where(mask[:, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhst,bthd->bshd", probs, v).reshape(b, sq, nh * hd)
    return out @ p[prefix + "wo"].astype(c.dtype)


def _enc_layer(carry, p, *, c: T5Config, bias, mask, act_spec):
    x = carry
    h = _rms_norm(x, p["ln_attn"], c.rms_eps)
    x = x + _mha(h, h, p, "", c, bias, mask)
    h = _rms_norm(x, p["ln_mlp"], c.rms_eps)
    x = x + jax.nn.relu(h @ p["w_up"].astype(c.dtype)) @ p["w_down"].astype(c.dtype)
    if act_spec is not None:
        x = _constrain(x, act_spec)
    return x, None


def _dec_layer(carry, p, *, c: T5Config, bias, self_mask, enc_out, cross_mask, act_spec):
    x = carry
    h = _rms_norm(x, p["ln_attn"], c.rms_eps)
    x = x + _mha(h, h, p, "", c, bias, self_mask)
    h = _rms_norm(x, p["ln_cross"], c.rms_eps)
    x = x + _mha(h, enc_out, p, "cross_", c, None, cross_mask)
    h = _rms_norm(x, p["ln_mlp"], c.rms_eps)
    x = x + jax.nn.relu(h @ p["w_up"].astype(c.dtype)) @ p["w_down"].astype(c.dtype)
    if act_spec is not None:
        x = _constrain(x, act_spec)
    return x, None


def lm_head(params: dict, config: T5Config) -> jax.Array:
    """Tied head in compute dtype, scaled by 1/sqrt(d) (T5 convention) —
    single source for apply() and the chunked loss."""
    return params["shared_embed"].T.astype(config.dtype) / np.sqrt(config.hidden_size)


def apply(
    params: dict,
    input_ids: jax.Array,
    decoder_input_ids: jax.Array,
    config: T5Config,
    attention_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """(encoder ids [B, S], decoder ids [B, T]) -> fp32 logits [B, T, V]."""
    hidden = apply_hidden(params, input_ids, decoder_input_ids, config, attention_mask)
    return (hidden @ lm_head(params, config)).astype(jnp.float32)


def apply_hidden(
    params: dict,
    input_ids: jax.Array,
    decoder_input_ids: jax.Array,
    config: T5Config,
    attention_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Encoder+decoder trunk -> final-normed decoder hidden [B, T, d]."""
    c = config
    b, s = input_ids.shape
    t = decoder_input_ids.shape[1]
    act_spec = P(("dcn_dp", "dp", "fsdp"), None, None)

    enc_out = encode(params, input_ids, c, attention_mask, act_spec=act_spec)

    dec_bias = _rel_bias(params["dec_rel_bias"].astype(jnp.float32), t, t, c, bidirectional=False)
    self_mask = jnp.broadcast_to(jnp.tril(jnp.ones((t, t), bool)), (b, t, t))
    cross_mask = None
    if attention_mask is not None:
        cross_mask = jnp.broadcast_to(attention_mask.astype(bool)[:, None, :], (b, t, s))

    y = _embed_lookup(params["shared_embed"], decoder_input_ids, c.dtype)
    y = _constrain(y, act_spec)

    def dec_body(carry, lp):
        return _dec_layer(
            carry, _dequant_layer(lp), c=c, bias=dec_bias, self_mask=self_mask,
            enc_out=enc_out, cross_mask=cross_mask, act_spec=act_spec,
        )

    if c.remat:
        dec_body = jax.checkpoint(dec_body, policy=jax.checkpoint_policies.nothing_saveable)
    y, _ = jax.lax.scan(dec_body, y, params["decoder"])
    return _rms_norm(y, params["dec_final_ln"], c.rms_eps)


def loss_fn(params: dict, batch: dict, config: T5Config) -> jax.Array:
    """Seq2seq cross-entropy: batch needs input_ids, decoder_input_ids, labels
    (and optional attention_mask); labels < 0 are ignored.

    ``config.loss_impl == "chunked"`` streams the head matmul over vocab
    tiles (``ops/chunked_ce.py``) — no [B, T, V] logits tensor."""
    from .llama import cross_entropy

    labels = batch["labels"]
    weights = (labels >= 0).astype(jnp.float32)
    labels = jnp.maximum(labels, 0)
    if config.loss_impl == "chunked":
        from ..ops.chunked_ce import chunked_cross_entropy

        hidden = apply_hidden(
            params,
            batch["input_ids"],
            batch["decoder_input_ids"],
            config,
            attention_mask=batch.get("attention_mask"),
        )
        return chunked_cross_entropy(
            hidden, lm_head(params, config), labels, weights, config.loss_chunk_size
        )
    logits = apply(
        params,
        batch["input_ids"],
        batch["decoder_input_ids"],
        config,
        attention_mask=batch.get("attention_mask"),
    )
    return cross_entropy(logits, labels, weights)


# ---------------------------------------------------------------------------
# Encoder-decoder KV-cache inference
# ---------------------------------------------------------------------------
#
# Cross-attention K/V depend only on the encoder output, so they are computed
# ONCE at prefill; the decoder self-attention carries a per-layer KV cache like
# the causal families (models/generation.py driver shapes).


def _rel_bias_at(table: jax.Array, q_positions: jax.Array, k_len: int, c: "T5Config"):
    """Relative bias for queries at absolute ``q_positions`` ([T]) against keys
    0..k_len — the decode-time generalization of ``_rel_bias``."""
    mem = jnp.arange(k_len)[None, :]
    buckets = _relative_buckets(mem - q_positions[:, None], c.num_buckets, c.max_distance, False)
    return table[buckets].transpose(2, 0, 1)  # [H, T, k_len]


def encode(params: dict, input_ids: jax.Array, config: "T5Config",
           attention_mask: Optional[jax.Array] = None, act_spec=None) -> jax.Array:
    """Encoder stack only -> [B, S, D] (shared by apply and generation)."""
    c = config
    b, s = input_ids.shape
    enc_mask = None
    if attention_mask is not None:
        valid = attention_mask.astype(bool)
        enc_mask = valid[:, None, :] & valid[:, :, None]
    enc_bias = _rel_bias(params["enc_rel_bias"].astype(jnp.float32), s, s, c, bidirectional=True)
    x = _embed_lookup(params["shared_embed"], input_ids, c.dtype)
    if act_spec is not None:
        x = _constrain(x, act_spec)

    def enc_body(carry, lp):
        return _enc_layer(carry, _dequant_layer(lp), c=c, bias=enc_bias, mask=enc_mask,
                          act_spec=act_spec)

    if c.remat:
        enc_body = jax.checkpoint(enc_body, policy=jax.checkpoint_policies.nothing_saveable)
    x, _ = jax.lax.scan(enc_body, x, params["encoder"])
    return _rms_norm(x, params["enc_final_ln"], c.rms_eps)


def quantize_weights(params: dict, block_size: int = 64) -> dict:
    """int8-weight-resident storage for both stacks (encoder + decoder);
    shared embedding, rel-bias tables and norms stay full precision.  See
    ``llama.quantize_weights``."""
    from ..utils.quantization import quantize_layer_stack

    out = dict(params)
    out["encoder"] = quantize_layer_stack(params["encoder"], block_size)
    out["decoder"] = quantize_layer_stack(params["decoder"], block_size)
    return out


def init_decoder_cache(params: dict, enc_out: jax.Array, config: "T5Config", max_len: int) -> dict:
    """Self-attn KV cache + precomputed per-layer cross-attention K/V."""
    c = config
    b, s, _ = enc_out.shape
    hd, nh = c.head_dim, c.num_heads

    def cross_kv(lp):
        lp = _dequant_layer(lp)
        k = (enc_out @ lp["cross_wk"].astype(c.dtype)).reshape(b, s, nh, hd)
        v = (enc_out @ lp["cross_wv"].astype(c.dtype)).reshape(b, s, nh, hd)
        return k, v

    cross_k, cross_v = jax.lax.map(cross_kv, params["decoder"])
    from .generation import make_kv_cache

    cache = make_kv_cache(
        c.num_layers, b, max_len, nh, hd, c.dtype,
        quantized=getattr(c, "kv_cache_quant", False),
    )
    # Cross K/V stay full precision: computed once per call, read every
    # token — quantizing them trades accuracy for memory only while the
    # (short-lived) cache exists; the growing self-attn cache is the win.
    cache["cross_k"] = cross_k  # [L, B, S, H, hd]
    cache["cross_v"] = cross_v
    return cache


def decode_cached(
    params: dict,
    decoder_input_ids: jax.Array,
    config: "T5Config",
    cache: dict,
    attention_mask: Optional[jax.Array] = None,
    num_beams: int = 1,
) -> tuple[jax.Array, dict]:
    """Decoder forward over new tokens at positions index..index+T with
    self-attn cache read/write and precomputed cross K/V.

    ``num_beams > 1``: the decoder batch is ``B*num_beams`` (tiled self
    cache) while the cross K/V and ``attention_mask`` stay at batch ``B`` —
    beams fold into the cross attention as a grouped einsum instead of
    tiling the encode output K-fold in HBM."""
    from .generation import check_cache_room

    c = config
    b, t = decoder_input_ids.shape
    hd, nh = c.head_dim, c.num_heads
    index = cache["index"]
    max_len = cache["k"].shape[2]
    check_cache_room(index, t, max_len)
    s = cache["cross_k"].shape[2]  # encoder length lives in the cross cache
    if b % num_beams:
        raise ValueError(f"decoder batch {b} not divisible by num_beams {num_beams}")
    b0 = b // num_beams

    positions = index + jnp.arange(t)
    bias = _rel_bias_at(params["dec_rel_bias"].astype(jnp.float32), positions, max_len, c)
    k_pos = jnp.arange(max_len)
    self_mask = jnp.broadcast_to(positions[:, None] >= k_pos[None, :], (b, t, max_len))
    cross_mask = None
    if attention_mask is not None:
        cross_mask = jnp.broadcast_to(attention_mask.astype(bool)[:, None, :], (b0, t, s))

    y = _embed_lookup(params["shared_embed"], decoder_input_ids, c.dtype)

    from .generation import cache_write

    def body(carry, xs):
        lp, ck, cv, xk, xv = xs
        lp = _dequant_layer(lp)
        x = carry
        # Self-attention against the cache (plain or int8 via cache_write).
        h = _rms_norm(x, lp["ln_attn"], c.rms_eps)
        q = (h @ lp["wq"].astype(c.dtype)).reshape(b, t, nh, hd)
        k = (h @ lp["wk"].astype(c.dtype)).reshape(b, t, nh, hd)
        v = (h @ lp["wv"].astype(c.dtype)).reshape(b, t, nh, hd)
        ck, k_full = cache_write(ck, k, index, c.dtype)
        cv, v_full = cache_write(cv, v, index, c.dtype)
        scores = jnp.einsum("bshd,bthd->bhst", q, k_full).astype(jnp.float32) + bias[None]
        scores = jnp.where(self_mask[:, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(v_full.dtype)
        attn = jnp.einsum("bhst,bthd->bshd", probs, v_full).reshape(b, t, nh * hd)
        x = x + attn @ lp["wo"].astype(c.dtype)
        # Cross-attention against precomputed encoder K/V (batch b0; beams
        # fold via the grouped einsum — no K-fold tile of the encode output).
        h = _rms_norm(x, lp["ln_cross"], c.rms_eps)
        q = (h @ lp["cross_wq"].astype(c.dtype)).reshape(b0, num_beams, t, nh, hd)
        scores = jnp.einsum("bkthd,bshd->bkhts", q, xk).astype(jnp.float32)
        if cross_mask is not None:
            scores = jnp.where(cross_mask[:, None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(xv.dtype)
        attn = jnp.einsum("bkhts,bshd->bkthd", probs, xv).reshape(b, t, nh * hd)
        x = x + attn @ lp["cross_wo"].astype(c.dtype)
        # MLP.
        h = _rms_norm(x, lp["ln_mlp"], c.rms_eps)
        x = x + jax.nn.relu(h @ lp["w_up"].astype(c.dtype)) @ lp["w_down"].astype(c.dtype)
        return x, (ck, cv)

    from .generation import pack_cache_for_scan, unpack_cache_from_scan

    ck_in, cv_in, quant = pack_cache_for_scan(cache)
    y, (new_k, new_v) = jax.lax.scan(
        body, y, (params["decoder"], ck_in, cv_in, cache["cross_k"], cache["cross_v"])
    )
    y = _rms_norm(y, params["dec_final_ln"], c.rms_eps)
    logits = (y @ lm_head(params, c)).astype(jnp.float32)
    new_cache = dict(cache)
    new_cache.update(unpack_cache_from_scan(new_k, new_v, index + t, quant))
    return logits, new_cache


def generate(
    params: dict,
    input_ids: jax.Array,
    config: "T5Config",
    max_new_tokens: int,
    decoder_start_token_id: int = 0,
    temperature: float = 0.0,
    key=None,
    attention_mask: Optional[jax.Array] = None,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jax.Array:
    """Seq2seq generation: encode once, then autoregressive decode with the
    self-attn cache + precomputed cross K/V.  Returns decoder ids
    ``[B, 1 + max_new_tokens]`` (leading start token)."""
    from .generation import generate_loop

    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1 for seq2seq generation")
    c = config
    b = input_ids.shape[0]
    enc_out = encode(params, input_ids, c, attention_mask)

    def _init_cache(cfg, batch_size, max_len):
        return init_decoder_cache(params, enc_out, cfg, max_len)

    def _apply_cached(p, ids, cfg, cache):
        return decode_cached(p, ids, cfg, cache, attention_mask)

    start = jnp.full((b, 1), decoder_start_token_id, jnp.int32)
    return generate_loop(
        _apply_cached, _init_cache, params, start, c,
        max_new_tokens, temperature=temperature, key=key,
        top_k=top_k, top_p=top_p,
    )


def generate_beam(
    params: dict,
    input_ids: jax.Array,
    config: "T5Config",
    max_new_tokens: int,
    num_beams: int = 4,
    length_penalty: float = 1.0,
    eos_token_id: Optional[int] = None,
    decoder_start_token_id: int = 0,
    attention_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Seq2seq beam search: encode once, beam-decode with the shared
    machinery (``models/generation.py beam_search``).  Only the self-attn
    cache tiles per beam; the per-layer cross K/V and the source
    ``attention_mask`` stay at batch ``B`` — beams fold into the cross
    attention as a grouped einsum (``decode_cached(num_beams=K)``), so the
    encode output is never duplicated K-fold in HBM.  Returns decoder ids
    ``[B, 1 + max_new_tokens]``."""
    from .generation import beam_search

    c = config
    b = input_ids.shape[0]
    enc_out = encode(params, input_ids, c, attention_mask)
    cross: dict = {}

    def _init_cache(cfg, batch_size, max_len):
        cache = init_decoder_cache(params, enc_out, cfg, max_len)
        # Keep the cross K/V OUT of the cache beam_search tiles/reorders:
        # all K beams of a batch row share the same encode output, so tiling
        # would K-fold its HBM and gather-copy it every decode step for
        # nothing — decode_cached folds beams via a grouped einsum instead.
        cross["cross_k"] = cache.pop("cross_k")
        cross["cross_v"] = cache.pop("cross_v")
        return cache

    def _apply_cached(p, ids, cfg, cache):
        # Prefill runs at batch B (shared prompt); decode steps at B*K.
        beams = 1 if ids.shape[0] == b else num_beams
        full = dict(cache)
        full.update(cross)
        logits, new_cache = decode_cached(
            p, ids, cfg, full, attention_mask, num_beams=beams
        )
        new_cache = dict(new_cache)
        new_cache.pop("cross_k")
        new_cache.pop("cross_v")
        return logits, new_cache

    start = jnp.full((b, 1), decoder_start_token_id, jnp.int32)
    return beam_search(
        _apply_cached, _init_cache, params, start, c, max_new_tokens,
        num_beams=num_beams, length_penalty=length_penalty,
        eos_token_id=eos_token_id,
    )


def speculative_generate(
    params: dict,
    draft_params: dict,
    input_ids: jax.Array,
    config: "T5Config",
    draft_config: "T5Config",
    max_new_tokens: int,
    num_draft_tokens: int = 4,
    decoder_start_token_id: int = 0,
    attention_mask: Optional[jax.Array] = None,
    return_stats: bool = False,
    temperature: float = 0.0,
    key=None,
) -> jax.Array:
    """Speculative seq2seq decoding: both models encode the source once,
    then the draft decoder proposes and the target decoder verifies (see
    ``models/generation.py speculative_generate_loop``).  Greedy by default
    (token-identical to ``generate(..., temperature=0)``); ``temperature>0``
    + ``key`` runs the distribution-exact sampling mode.  Batch 1 only."""
    from .generation import speculative_generate_loop

    c = config
    b = input_ids.shape[0]
    enc_out = encode(params, input_ids, c, attention_mask)
    d_enc_out = encode(draft_params, input_ids, draft_config, attention_mask)

    def _init_cache(cfg, batch_size, max_len):
        return init_decoder_cache(params, enc_out, cfg, max_len)

    def _apply_cached(p, ids, cfg, cache):
        return decode_cached(p, ids, cfg, cache, attention_mask)

    def _d_init_cache(cfg, batch_size, max_len):
        return init_decoder_cache(draft_params, d_enc_out, cfg, max_len)

    start = jnp.full((b, 1), decoder_start_token_id, jnp.int32)
    return speculative_generate_loop(
        _apply_cached, _init_cache, params, c,
        _apply_cached, _d_init_cache, draft_params, draft_config,
        start, max_new_tokens,
        num_draft_tokens=num_draft_tokens, return_stats=return_stats,
        temperature=temperature, key=key,
    )
