"""ResNet — native convolutional model family.

Parity rationale: the reference's CV story is torchvision ResNets through
its model-agnostic loop (``examples/cv_example.py`` uses
``torchvision.models.resnet50``; the BASELINE target row is "ResNet-50
data-parallel over a TPU mesh"), with ``torch.nn.SyncBatchNorm`` as the
cross-replica statistics mechanism under DDP.  This family covers the
conv-residual architecture class natively so CNN training does not
require the torch bridge.

TPU-first design notes:

- **NHWC layout** (`channels-last`) throughout — the TPU-native conv
  layout; XLA lowers ``lax.conv_general_dilated`` onto the MXU as an
  implicit im2col matmul, so convs live on the systolic array like every
  other contraction in this package.  Compute in bf16, params fp32.
- **SyncBatchNorm is free under GSPMD.**  The reference needs a special
  module (``SyncBatchNorm.convert_sync_batchnorm``) because each DDP
  process sees only its local batch.  Here the batch axis is *sharded,
  not split*: ``jnp.mean`` over a ``("dp","fsdp")``-sharded batch is the
  global mean — XLA inserts the cross-replica reduction.  Plain
  batch-norm code IS sync batch-norm on the mesh.
- **Functional batch statistics.**  Running mean/var are carried in an
  explicit ``batch_stats`` pytree returned from ``apply`` (no module
  state): train steps thread it like optimizer state, eval uses it
  frozen.  This is the idiomatic JAX replacement for torch's mutable
  ``running_mean``/``running_var`` buffers.
- **Stage-wise ``lax.scan``.**  Every stage's tail blocks share shapes,
  so they are stacked and scanned (compile time stays O(stages), not
  O(depth)); the shape-changing first block of each stage (projection
  shortcut, stride) is unrolled.

Reference surface covered (capability, not code): torchvision-class
ResNet-18/34 (basic block) and ResNet-50/101/152 (bottleneck), plus the
reference's SyncBatchNorm semantics (see above).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.sharding import constrain as _constrain

__all__ = [
    "ResNetConfig",
    "init_params",
    "init_batch_stats",
    "apply",
    "classification_loss_fn",
    "PARTITION_RULES",
    "param_specs",
]


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    block: str = "bottleneck"  # "basic" (18/34) | "bottleneck" (50/101/152)
    stage_sizes: tuple = (3, 4, 6, 3)  # ResNet-50
    width: int = 64  # first-stage channel width
    num_channels: int = 3
    num_labels: int = 1000
    bn_eps: float = 1e-5
    bn_momentum: float = 0.9  # running = m*running + (1-m)*batch
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    stem: str = "imagenet"  # 7x7/2 + maxpool | "cifar": 3x3/1, no pool
    remat: bool = False

    def __post_init__(self):
        if self.block not in ("basic", "bottleneck"):
            raise ValueError(f"block must be 'basic' or 'bottleneck', got {self.block!r}")
        if self.stem not in ("imagenet", "cifar"):
            raise ValueError(f"stem must be 'imagenet' or 'cifar', got {self.stem!r}")

    @property
    def expansion(self) -> int:
        return 4 if self.block == "bottleneck" else 1

    def stage_channels(self, stage: int) -> int:
        return self.width * (2**stage)

    def num_params(self) -> int:
        leaves = jax.tree_util.tree_leaves(
            _param_shapes(self), is_leaf=lambda x: isinstance(x, tuple)
        )
        return sum(int(np.prod(s)) for s in leaves)

    def largest_block_f32_bytes(self) -> int:
        """Largest top-level block (stem / one stage / classifier) in fp32
        bytes — the estimate-memory "largest layer" hook.  Stages are far
        from equal-sized (ResNet-50's stage3 holds ~59% of the params), so
        this is computed exactly from the shape tree."""

        def block_bytes(tree) -> int:
            leaves = jax.tree_util.tree_leaves(
                tree, is_leaf=lambda x: isinstance(x, tuple)
            )
            return sum(int(np.prod(s)) for s in leaves) * 4

        return max(block_bytes(v) for v in _param_shapes(self).values())

    @classmethod
    def tiny(cls, **kw) -> "ResNetConfig":
        defaults = dict(
            block="basic", stage_sizes=(2, 2), width=8, num_labels=10, stem="cifar"
        )
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def resnet18(cls, **kw) -> "ResNetConfig":
        defaults = dict(block="basic", stage_sizes=(2, 2, 2, 2))
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def resnet34(cls, **kw) -> "ResNetConfig":
        defaults = dict(block="basic", stage_sizes=(3, 4, 6, 3))
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def resnet50(cls, **kw) -> "ResNetConfig":
        return cls(**kw)  # the defaults are ResNet-50

    @classmethod
    def resnet101(cls, **kw) -> "ResNetConfig":
        defaults = dict(stage_sizes=(3, 4, 23, 3))
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def resnet152(cls, **kw) -> "ResNetConfig":
        defaults = dict(stage_sizes=(3, 8, 36, 3))
        defaults.update(kw)
        return cls(**defaults)


# Conv kernels are HWIO; shard the output-channel dim over fsdp (the axis
# that shards parameters).  BN params are per-channel vectors — replicated.
# The classifier matmul takes tp like the other families' heads.
PARTITION_RULES: list[tuple[str, P]] = [
    (r"stem/conv", P(None, None, None, "fsdp")),
    (r"/conv\d_w$", P(None, None, None, "fsdp")),
    (r"/proj_w$", P(None, None, None, "fsdp")),
    (r"classifier/w", P(None, "tp")),
]


def _block_shapes(c: ResNetConfig, cin: int, cout: int) -> dict:
    """Shapes for one residual block with input ``cin`` -> output
    ``cout * expansion`` channels (no projection entry; the caller adds it
    for shape-changing blocks)."""
    if c.block == "basic":
        return {
            "conv1_w": (3, 3, cin, cout),
            "bn1_scale": (cout,),
            "bn1_bias": (cout,),
            "conv2_w": (3, 3, cout, cout),
            "bn2_scale": (cout,),
            "bn2_bias": (cout,),
        }
    return {
        "conv1_w": (1, 1, cin, cout),
        "bn1_scale": (cout,),
        "bn1_bias": (cout,),
        "conv2_w": (3, 3, cout, cout),
        "bn2_scale": (cout,),
        "bn2_bias": (cout,),
        "conv3_w": (1, 1, cout, cout * 4),
        "bn3_scale": (cout * 4,),
        "bn3_bias": (cout * 4,),
    }


def _stack(shapes: dict, n: int) -> dict:
    return {k: (n, *v) for k, v in shapes.items()}


def _param_shapes(c: ResNetConfig) -> dict:
    e = c.expansion
    stem_k = 7 if c.stem == "imagenet" else 3
    out: dict = {
        "stem": {
            "conv_w": (stem_k, stem_k, c.num_channels, c.width),
            "bn_scale": (c.width,),
            "bn_bias": (c.width,),
        }
    }
    cin = c.width
    for s, n in enumerate(c.stage_sizes):
        cout = c.stage_channels(s)
        head = _block_shapes(c, cin, cout)
        # Projection shortcut only where the residual shapes change
        # (torchvision parity: basic-block stage 0 keeps the identity).
        if s > 0 or cin != cout * e:
            head["proj_w"] = (1, 1, cin, cout * e)
            head["proj_bn_scale"] = (cout * e,)
            head["proj_bn_bias"] = (cout * e,)
        stage: dict = {"head": head}
        if n > 1:
            stage["tail"] = _stack(_block_shapes(c, cout * e, cout), n - 1)
        out[f"stage{s}"] = stage
        cin = cout * e
    out["classifier"] = {"w": (cin, c.num_labels), "b": (c.num_labels,)}
    return out


def _stats_shapes(c: ResNetConfig) -> dict:
    """batch_stats pytree shapes: a {mean, var} pair per BN site, mirroring
    the param-tree layout so the two trees zip in ``apply``."""

    def per_site(shapes: dict) -> dict:
        out = {}
        for k, v in shapes.items():
            if k.endswith("_scale"):
                site = k[: -len("_scale")]
                out[f"{site}_mean"] = v
                out[f"{site}_var"] = v
        return out

    params = _param_shapes(c)
    out: dict = {"stem": per_site(params["stem"])}
    for s in range(len(c.stage_sizes)):
        stage = {"head": per_site(params[f"stage{s}"]["head"])}
        if "tail" in params[f"stage{s}"]:
            stage["tail"] = per_site(params[f"stage{s}"]["tail"])
        out[f"stage{s}"] = stage
    return out


def param_specs(config: ResNetConfig) -> dict:
    from ..parallel.sharding import spec_from_rules

    shapes = _param_shapes(config)

    def one(kp, shape):
        path = "/".join(str(getattr(k, "key", k)) for k in kp)
        ndim = len(shape)
        # Stacked tail blocks carry a leading layer dim; match rules against
        # the per-block rank and prepend a replicated leading axis.
        if "tail" in path.split("/"):
            spec = spec_from_rules(path, ndim - 1, PARTITION_RULES)
            if spec is not None:
                return P(None, *spec)
            return P(*([None] * ndim))
        spec = spec_from_rules(path, ndim, PARTITION_RULES)
        return spec if spec is not None else P(*([None] * ndim))

    return jax.tree_util.tree_map_with_path(one, shapes, is_leaf=lambda x: isinstance(x, tuple))


def init_params(config: ResNetConfig, key: jax.Array) -> dict:
    shapes = _param_shapes(config)
    leaves, treedef = jax.tree_util.tree_flatten(shapes, is_leaf=lambda x: isinstance(x, tuple))
    keys = jax.tree_util.tree_unflatten(treedef, list(jax.random.split(key, len(leaves))))
    last_bn = "bn3" if config.block == "bottleneck" else "bn2"

    def init_one(kp, shape, k):
        # Dispatch on the param NAME (see the family-wide init-hardening
        # note: shape dispatch misfires on dimension coincidences).
        name = str(getattr(kp[-1], "key", kp[-1]))
        if name.endswith("_scale"):
            # Zero-init the residual branch's last BN scale so every block
            # starts as identity (the standard ResNet trick); all other BN
            # scales start at one.
            if name.startswith(last_bn) and "stage" in str(kp[0]):
                return jnp.zeros(shape, config.param_dtype)
            return jnp.ones(shape, config.param_dtype)
        if name.endswith("_bias") or name == "b":
            return jnp.zeros(shape, config.param_dtype)
        # He fan-in init for conv kernels (fan_in = kh*kw*cin) and the fc.
        fan_in = int(np.prod(shape[-4:-1])) if len(shape) >= 4 else shape[-2]
        std = np.sqrt(2.0 / max(fan_in, 1))
        return (jax.random.normal(k, shape, jnp.float32) * std).astype(config.param_dtype)

    return jax.tree_util.tree_map_with_path(
        init_one, shapes, keys, is_leaf=lambda x: isinstance(x, tuple)
    )


def init_batch_stats(config: ResNetConfig) -> dict:
    def one(kp, shape):
        name = str(getattr(kp[-1], "key", kp[-1]))
        fill = jnp.ones if name.endswith("_var") else jnp.zeros
        return fill(shape, jnp.float32)

    return jax.tree_util.tree_map_with_path(
        one, _stats_shapes(config), is_leaf=lambda x: isinstance(x, tuple)
    )


def _conv(x: jax.Array, w: jax.Array, stride: int, c: ResNetConfig) -> jax.Array:
    # Explicit symmetric padding (torch Conv2d padding=k//2), NOT "SAME":
    # XLA's SAME pads asymmetrically for stride 2 ((0,1) vs torch's (1,1)),
    # which would misalign every strided conv by one pixel vs a torch/HF
    # checkpoint.
    k = w.shape[0]
    pad = (k - 1) // 2
    return jax.lax.conv_general_dilated(
        x,
        w.astype(c.dtype),
        window_strides=(stride, stride),
        padding=((pad, pad), (pad, pad)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _batch_norm(x, scale, bias, mean, var, new_stats, site, c: ResNetConfig, train: bool):
    """Normalize over (N, H, W).  Under a GSPMD mesh with the batch axis
    sharded, these means ARE the global cross-replica statistics (XLA
    inserts the reduction) — the reference's SyncBatchNorm without a
    special module.  ``new_stats[site_mean/ site_var]`` is written with the
    momentum update when ``train``."""
    if train:
        xf = x.astype(jnp.float32)
        bmean = xf.mean(axis=(0, 1, 2))
        bvar = xf.var(axis=(0, 1, 2))
        m = c.bn_momentum
        # torch BatchNorm semantics: normalize with the biased batch var,
        # update the running estimate with the unbiased (ddof=1) one.
        n = x.shape[0] * x.shape[1] * x.shape[2]
        unbiased = bvar * (n / max(n - 1, 1))
        new_stats[f"{site}_mean"] = m * mean + (1.0 - m) * bmean
        new_stats[f"{site}_var"] = m * var + (1.0 - m) * unbiased
        use_mean, use_var = bmean, bvar
    else:
        new_stats[f"{site}_mean"] = mean
        new_stats[f"{site}_var"] = var
        use_mean, use_var = mean, var
    inv = jax.lax.rsqrt(use_var + c.bn_eps) * scale.astype(jnp.float32)
    out = (x.astype(jnp.float32) - use_mean) * inv + bias.astype(jnp.float32)
    return out.astype(c.dtype)


def _block(x, p, stats, c: ResNetConfig, stride: int, train: bool):
    """One residual block; returns (out, new_stats_for_block)."""
    ns: dict = {}
    shortcut = x
    if c.block == "basic":
        h = _conv(x, p["conv1_w"], stride, c)
        h = jax.nn.relu(
            _batch_norm(h, p["bn1_scale"], p["bn1_bias"], stats["bn1_mean"],
                        stats["bn1_var"], ns, "bn1", c, train)
        )
        h = _conv(h, p["conv2_w"], 1, c)
        h = _batch_norm(h, p["bn2_scale"], p["bn2_bias"], stats["bn2_mean"],
                        stats["bn2_var"], ns, "bn2", c, train)
    else:
        h = _conv(x, p["conv1_w"], 1, c)
        h = jax.nn.relu(
            _batch_norm(h, p["bn1_scale"], p["bn1_bias"], stats["bn1_mean"],
                        stats["bn1_var"], ns, "bn1", c, train)
        )
        h = _conv(h, p["conv2_w"], stride, c)
        h = jax.nn.relu(
            _batch_norm(h, p["bn2_scale"], p["bn2_bias"], stats["bn2_mean"],
                        stats["bn2_var"], ns, "bn2", c, train)
        )
        h = _conv(h, p["conv3_w"], 1, c)
        h = _batch_norm(h, p["bn3_scale"], p["bn3_bias"], stats["bn3_mean"],
                        stats["bn3_var"], ns, "bn3", c, train)
    if "proj_w" in p:
        shortcut = _conv(x, p["proj_w"], stride, c)
        shortcut = _batch_norm(
            shortcut, p["proj_bn_scale"], p["proj_bn_bias"], stats["proj_bn_mean"],
            stats["proj_bn_var"], ns, "proj_bn", c, train,
        )
    return jax.nn.relu(h + shortcut), ns


def apply(params: dict, batch_stats: dict, pixels: jax.Array, config: ResNetConfig,
          train: bool = False) -> tuple[jax.Array, dict]:
    """Returns (pooled features [B, C_out] fp32, new_batch_stats).

    ``pixels`` is channels-last ``[B, H, W, C]`` (NHWC is the TPU conv
    layout; transpose NCHW inputs before calling).  In eval (``train=False``)
    the returned stats equal the input stats.
    """
    c = config
    new_stats: dict = {"stem": {}}
    x = pixels.astype(c.dtype)
    x = _constrain(x, P(("dcn_dp", "dp", "fsdp"), None, None, None))
    s = params["stem"]
    x = _conv(x, s["conv_w"], 2 if c.stem == "imagenet" else 1, c)
    x = jax.nn.relu(
        _batch_norm(x, s["bn_scale"], s["bn_bias"], batch_stats["stem"]["bn_mean"],
                    batch_stats["stem"]["bn_var"], new_stats["stem"], "bn", c, train)
    )
    if c.stem == "imagenet":
        # torch MaxPool2d(3, stride=2, padding=1): symmetric explicit pad.
        x = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
            ((0, 0), (1, 1), (1, 1), (0, 0)),
        )

    for si, n in enumerate(c.stage_sizes):
        stage_p = params[f"stage{si}"]
        stage_s = batch_stats[f"stage{si}"]
        stride = 1 if si == 0 else 2
        sns: dict = {}

        def head_fn(x):
            return _block(x, stage_p["head"], stage_s["head"], c, stride, train)

        if c.remat:
            head_fn = jax.checkpoint(head_fn)
        x, sns["head"] = head_fn(x)

        if n > 1:
            def body(carry, pl_sl):
                pl, sl = pl_sl
                out, ns = _block(carry, pl, sl, c, 1, train)
                return out, ns

            if c.remat:
                body = jax.checkpoint(body)
            x, sns["tail"] = jax.lax.scan(body, x, (stage_p["tail"], stage_s["tail"]))
        new_stats[f"stage{si}"] = sns

    pooled = x.astype(jnp.float32).mean(axis=(1, 2))
    return pooled, new_stats


def classification_loss_fn(params: dict, batch_stats: dict, batch: dict,
                           config: ResNetConfig, train: bool = True):
    """Cross-entropy over ``batch["pixel_values"]`` [B, H, W, C] and
    ``batch["labels"]`` [B].  Returns ``(loss, new_batch_stats)`` — use with
    ``jax.value_and_grad(..., has_aux=True)`` and thread the stats like
    optimizer state (they are not differentiated)."""
    pooled, new_stats = apply(params, batch_stats, batch["pixel_values"], config, train=train)
    logits = pooled @ params["classifier"]["w"].astype(jnp.float32) + params["classifier"]["b"]
    logp = jax.nn.log_softmax(logits, axis=-1)
    loss = -jnp.mean(jnp.take_along_axis(logp, batch["labels"][:, None], axis=-1))
    return loss, new_stats
