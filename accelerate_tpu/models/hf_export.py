"""HF-checkpoint export: native param trees -> transformers-loadable
checkpoints.

The inverse of ``hf_import``: after training or quant-aware work on the
native families, write a ``config.json`` + ``model.safetensors`` directory
that ``transformers.AutoModel*.from_pretrained`` loads directly — the
interop contract that lets work leave this framework as easily as it
enters (reference frame: every reference workflow ends in
``save_pretrained``; ``accelerator.save_model`` keeps torch modules in the
HF layout, and this does the same for native pytrees).

Oracles (``tests/test_hf_export.py``): transformers loads the exported
directory and its forward matches the native logits; import(export(x))
round-trips bit-exactly.
"""

from __future__ import annotations

import json
import os

import numpy as np

import jax

__all__ = ["export_state_dict", "export_hf_checkpoint"]


def _np32(a) -> np.ndarray:
    return np.asarray(jax.device_get(a), np.float32)


def _unstack(tree_leaf, fmt: str, out: dict, transpose: bool = False):
    a = _np32(tree_leaf)
    for i in range(a.shape[0]):
        out[fmt.format(i)] = a[i].T.copy() if transpose else a[i].copy()


def _export_llama(params: dict, cfg) -> dict:
    # One source of truth: config.json's attention_bias must match whether
    # bias tensors exist, or from_pretrained silently drops/initializes them.
    if ("bq" in params["layers"]) != bool(cfg.attention_bias):
        raise ValueError(
            "attention_bias mismatch: params "
            f"{'contain' if 'bq' in params['layers'] else 'lack'} bias "
            f"tensors but cfg.attention_bias={cfg.attention_bias}; rebuild "
            "the config with the flag matching the params."
        )
    sd: dict = {"model.embed_tokens.weight": _np32(params["embed"])}
    lay = params["layers"]
    pre = "model.layers.{}."
    _unstack(lay["wq"], pre + "self_attn.q_proj.weight", sd, transpose=True)
    _unstack(lay["wk"], pre + "self_attn.k_proj.weight", sd, transpose=True)
    _unstack(lay["wv"], pre + "self_attn.v_proj.weight", sd, transpose=True)
    _unstack(lay["wo"], pre + "self_attn.o_proj.weight", sd, transpose=True)
    _unstack(lay["w_gate"], pre + "mlp.gate_proj.weight", sd, transpose=True)
    _unstack(lay["w_up"], pre + "mlp.up_proj.weight", sd, transpose=True)
    _unstack(lay["w_down"], pre + "mlp.down_proj.weight", sd, transpose=True)
    if "bq" in lay:
        _unstack(lay["bq"], pre + "self_attn.q_proj.bias", sd)
        _unstack(lay["bk"], pre + "self_attn.k_proj.bias", sd)
        _unstack(lay["bv"], pre + "self_attn.v_proj.bias", sd)
        _unstack(lay["bo"], pre + "self_attn.o_proj.bias", sd)
    _unstack(lay["ln_attn"], pre + "input_layernorm.weight", sd)
    _unstack(lay["ln_mlp"], pre + "post_attention_layernorm.weight", sd)
    sd["model.norm.weight"] = _np32(params["final_norm"])
    if "lm_head" in params:
        sd["lm_head.weight"] = _np32(params["lm_head"]).T.copy()
    return sd


def _export_gpt2(params: dict, cfg) -> dict:
    sd: dict = {
        "transformer.wte.weight": _np32(params["wte"]),
        "transformer.wpe.weight": _np32(params["wpe"]),
        "transformer.ln_f.weight": _np32(params["final_ln_scale"]),
        "transformer.ln_f.bias": _np32(params["final_ln_bias"]),
    }
    lay = params["layers"]
    pre = "transformer.h.{}."
    # Conv1D layout ([in, out]): no transpose on export either.
    _unstack(lay["w_qkv"], pre + "attn.c_attn.weight", sd)
    _unstack(lay["b_qkv"], pre + "attn.c_attn.bias", sd)
    _unstack(lay["w_proj"], pre + "attn.c_proj.weight", sd)
    _unstack(lay["b_proj"], pre + "attn.c_proj.bias", sd)
    _unstack(lay["w_up"], pre + "mlp.c_fc.weight", sd)
    _unstack(lay["b_up"], pre + "mlp.c_fc.bias", sd)
    _unstack(lay["w_down"], pre + "mlp.c_proj.weight", sd)
    _unstack(lay["b_down"], pre + "mlp.c_proj.bias", sd)
    _unstack(lay["ln_attn_scale"], pre + "ln_1.weight", sd)
    _unstack(lay["ln_attn_bias"], pre + "ln_1.bias", sd)
    _unstack(lay["ln_mlp_scale"], pre + "ln_2.weight", sd)
    _unstack(lay["ln_mlp_bias"], pre + "ln_2.bias", sd)
    return sd


def _split3(a: np.ndarray) -> tuple:
    return np.split(a, 3, axis=-1)


def _export_bert(params: dict, cfg) -> dict:
    e = params["embeddings"]
    sd: dict = {
        "bert.embeddings.word_embeddings.weight": _np32(e["word"]),
        "bert.embeddings.position_embeddings.weight": _np32(e["position"]),
        "bert.embeddings.token_type_embeddings.weight": _np32(e["token_type"]),
        "bert.embeddings.LayerNorm.weight": _np32(e["ln_scale"]),
        "bert.embeddings.LayerNorm.bias": _np32(e["ln_bias"]),
        "bert.pooler.dense.weight": _np32(params["pooler"]["w"]).T.copy(),
        "bert.pooler.dense.bias": _np32(params["pooler"]["b"]),
        "classifier.weight": _np32(params["classifier"]["w"]).T.copy(),
        "classifier.bias": _np32(params["classifier"]["b"]),
    }
    lay = params["layers"]
    pre = "bert.encoder.layer.{}."
    wq = _np32(lay["w_qkv"])
    bq = _np32(lay["b_qkv"])
    for i in range(wq.shape[0]):
        qw, kw, vw = _split3(wq[i])
        qb, kb, vb = _split3(bq[i])
        for n, w, b in (("query", qw, qb), ("key", kw, kb), ("value", vw, vb)):
            sd[pre.format(i) + f"attention.self.{n}.weight"] = w.T.copy()
            sd[pre.format(i) + f"attention.self.{n}.bias"] = b.copy()
    _unstack(lay["w_proj"], pre + "attention.output.dense.weight", sd, transpose=True)
    _unstack(lay["b_proj"], pre + "attention.output.dense.bias", sd)
    _unstack(lay["w_up"], pre + "intermediate.dense.weight", sd, transpose=True)
    _unstack(lay["b_up"], pre + "intermediate.dense.bias", sd)
    _unstack(lay["w_down"], pre + "output.dense.weight", sd, transpose=True)
    _unstack(lay["b_down"], pre + "output.dense.bias", sd)
    _unstack(lay["ln_attn_scale"], pre + "attention.output.LayerNorm.weight", sd)
    _unstack(lay["ln_attn_bias"], pre + "attention.output.LayerNorm.bias", sd)
    _unstack(lay["ln_mlp_scale"], pre + "output.LayerNorm.weight", sd)
    _unstack(lay["ln_mlp_bias"], pre + "output.LayerNorm.bias", sd)
    return sd


def _export_t5_stack(stack: dict, prefix: str, decoder: bool, out: dict):
    pre = prefix + ".block.{}."
    _unstack(stack["wq"], pre + "layer.0.SelfAttention.q.weight", out, transpose=True)
    _unstack(stack["wk"], pre + "layer.0.SelfAttention.k.weight", out, transpose=True)
    _unstack(stack["wv"], pre + "layer.0.SelfAttention.v.weight", out, transpose=True)
    _unstack(stack["wo"], pre + "layer.0.SelfAttention.o.weight", out, transpose=True)
    _unstack(stack["ln_attn"], pre + "layer.0.layer_norm.weight", out)
    mlp = 2 if decoder else 1
    if decoder:
        _unstack(stack["cross_wq"], pre + "layer.1.EncDecAttention.q.weight", out, transpose=True)
        _unstack(stack["cross_wk"], pre + "layer.1.EncDecAttention.k.weight", out, transpose=True)
        _unstack(stack["cross_wv"], pre + "layer.1.EncDecAttention.v.weight", out, transpose=True)
        _unstack(stack["cross_wo"], pre + "layer.1.EncDecAttention.o.weight", out, transpose=True)
        _unstack(stack["ln_cross"], pre + "layer.1.layer_norm.weight", out)
    _unstack(stack["w_up"], pre + f"layer.{mlp}.DenseReluDense.wi.weight", out, transpose=True)
    _unstack(stack["w_down"], pre + f"layer.{mlp}.DenseReluDense.wo.weight", out, transpose=True)
    _unstack(stack["ln_mlp"], pre + f"layer.{mlp}.layer_norm.weight", out)


def _export_t5(params: dict, cfg) -> dict:
    sd: dict = {
        "shared.weight": _np32(params["shared_embed"]),
        "encoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight":
            _np32(params["enc_rel_bias"]),
        "decoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight":
            _np32(params["dec_rel_bias"]),
        "encoder.final_layer_norm.weight": _np32(params["enc_final_ln"]),
        "decoder.final_layer_norm.weight": _np32(params["dec_final_ln"]),
    }
    _export_t5_stack(params["encoder"], "encoder", False, sd)
    _export_t5_stack(params["decoder"], "decoder", True, sd)
    return sd


def _export_mixtral(params: dict, cfg) -> dict:
    sd: dict = {"model.embed_tokens.weight": _np32(params["embed"])}
    lay = params["layers"]
    pre = "model.layers.{}."
    _unstack(lay["wq"], pre + "self_attn.q_proj.weight", sd, transpose=True)
    _unstack(lay["wk"], pre + "self_attn.k_proj.weight", sd, transpose=True)
    _unstack(lay["wv"], pre + "self_attn.v_proj.weight", sd, transpose=True)
    _unstack(lay["wo"], pre + "self_attn.o_proj.weight", sd, transpose=True)
    _unstack(lay["router"], pre + "block_sparse_moe.gate.weight", sd, transpose=True)
    for which, key in (("w1", "w_gate"), ("w3", "w_up"), ("w2", "w_down")):
        a = _np32(lay[key])  # [L, E, in, out]
        for i in range(a.shape[0]):
            for j in range(a.shape[1]):
                sd[
                    f"model.layers.{i}.block_sparse_moe.experts.{j}.{which}.weight"
                ] = a[i, j].T.copy()
    _unstack(lay["ln_attn"], pre + "input_layernorm.weight", sd)
    _unstack(lay["ln_mlp"], pre + "post_attention_layernorm.weight", sd)
    sd["model.norm.weight"] = _np32(params["final_norm"])
    sd["lm_head.weight"] = _np32(params["lm_head"]).T.copy()
    return sd


def _export_vit(params: dict, cfg) -> dict:
    if cfg.pool != "cls":
        raise ValueError(
            "ViT export requires pool='cls': HF ViT always prepends a CLS "
            "token, so a pool='mean' model (no cls token, num_patches "
            "position slots) cannot be represented as a loadable HF "
            "checkpoint."
        )
    e = params["embeddings"]
    p, C = cfg.patch_size, cfg.num_channels
    d = cfg.hidden_size
    # Inverse of the import permutation: [p*p*C, d] -> conv [d, C, p, p].
    conv = _np32(e["patch_w"]).reshape(p, p, C, d).transpose(3, 2, 0, 1).copy()
    sd: dict = {
        "vit.embeddings.patch_embeddings.projection.weight": conv,
        "vit.embeddings.patch_embeddings.projection.bias": _np32(e["patch_b"]),
        "vit.embeddings.position_embeddings": _np32(e["position"])[None],
        "vit.layernorm.weight": _np32(params["final_ln"]["scale"]),
        "vit.layernorm.bias": _np32(params["final_ln"]["bias"]),
        "classifier.weight": _np32(params["classifier"]["w"]).T.copy(),
        "classifier.bias": _np32(params["classifier"]["b"]),
    }
    sd["vit.embeddings.cls_token"] = _np32(e["cls"])  # pool=='cls' guaranteed above
    lay = params["layers"]
    pre = "vit.encoder.layer.{}."
    wq = _np32(lay["w_qkv"])
    bq = _np32(lay["b_qkv"])
    for i in range(wq.shape[0]):
        qw, kw, vw = _split3(wq[i])
        qb, kb, vb = _split3(bq[i])
        for n, w, b in (("query", qw, qb), ("key", kw, kb), ("value", vw, vb)):
            sd[pre.format(i) + f"attention.attention.{n}.weight"] = w.T.copy()
            sd[pre.format(i) + f"attention.attention.{n}.bias"] = b.copy()
    _unstack(lay["w_proj"], pre + "attention.output.dense.weight", sd, transpose=True)
    _unstack(lay["b_proj"], pre + "attention.output.dense.bias", sd)
    _unstack(lay["w_up"], pre + "intermediate.dense.weight", sd, transpose=True)
    _unstack(lay["b_up"], pre + "intermediate.dense.bias", sd)
    _unstack(lay["w_down"], pre + "output.dense.weight", sd, transpose=True)
    _unstack(lay["b_down"], pre + "output.dense.bias", sd)
    _unstack(lay["ln_attn_scale"], pre + "layernorm_before.weight", sd)
    _unstack(lay["ln_attn_bias"], pre + "layernorm_before.bias", sd)
    _unstack(lay["ln_mlp_scale"], pre + "layernorm_after.weight", sd)
    _unstack(lay["ln_mlp_bias"], pre + "layernorm_after.bias", sd)
    return sd


def _export_resnet(tree: dict, cfg) -> dict:
    """Expects the ``{"params", "batch_stats"}`` pair the resnet import
    produces (BN running statistics are state, exported alongside)."""
    if not (isinstance(tree, dict) and "params" in tree and "batch_stats" in tree):
        raise ValueError(
            "resnet export takes {'params': ..., 'batch_stats': ...} — the "
            "pair resnet training threads (and hf_import returns)."
        )
    if cfg.stem != "imagenet":
        raise ValueError(
            "resnet export requires stem='imagenet' (HF ResNet has no "
            "CIFAR-stem variant)."
        )
    params, stats = tree["params"], tree["batch_stats"]

    def conv(a):  # HWIO -> OIHW
        return _np32(a).transpose(3, 2, 0, 1).copy()

    def bn(prefix, site, p, s, out):
        out[prefix + ".weight"] = _np32(p[f"{site}_scale"])
        out[prefix + ".bias"] = _np32(p[f"{site}_bias"])
        out[prefix + ".running_mean"] = _np32(s[f"{site}_mean"])
        out[prefix + ".running_var"] = _np32(s[f"{site}_var"])
        out[prefix + ".num_batches_tracked"] = np.zeros((), np.int64)

    n_convs = 3 if cfg.block == "bottleneck" else 2
    sd: dict = {
        "resnet.embedder.embedder.convolution.weight": conv(params["stem"]["conv_w"]),
        "classifier.1.weight": _np32(params["classifier"]["w"]).T.copy(),
        "classifier.1.bias": _np32(params["classifier"]["b"]),
    }
    bn("resnet.embedder.embedder.normalization", "bn",
       params["stem"], stats["stem"], sd)

    for s_i, depth in enumerate(cfg.stage_sizes):
        sp, ss = params[f"stage{s_i}"], stats[f"stage{s_i}"]

        def one_layer(i, p, st):
            lp = f"resnet.encoder.stages.{s_i}.layers.{i}."
            for j in range(n_convs):
                sd[lp + f"layer.{j}.convolution.weight"] = conv(p[f"conv{j + 1}_w"])
                bn(lp + f"layer.{j}.normalization", f"bn{j + 1}", p, st, sd)
            if "proj_w" in p:
                sd[lp + "shortcut.convolution.weight"] = conv(p["proj_w"])
                bn(lp + "shortcut.normalization", "proj_bn", p, st, sd)

        one_layer(0, sp["head"], ss["head"])
        if depth > 1:
            for i in range(1, depth):
                one_layer(
                    i,
                    {k: v[i - 1] for k, v in sp["tail"].items()},
                    {k: v[i - 1] for k, v in ss["tail"].items()},
                )
    return sd


_EXPORTERS = {
    "llama": _export_llama,
    "gpt2": _export_gpt2,
    "bert": _export_bert,
    "t5": _export_t5,
    "mixtral": _export_mixtral,
    "vit": _export_vit,
    "resnet": _export_resnet,
}


def _hf_config_dict(family: str, cfg, params: dict) -> dict:
    """The MLP width is read from the WEIGHTS, not reconstructed from the
    native config: bert/gpt2/vit configs don't carry it (the forward derives
    it from shapes), so a 4*hidden guess would write config.json claims that
    contradict the tensors for non-standard widths."""
    if family == "llama":
        common = {
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.hidden_size,
            "intermediate_size": cfg.intermediate_size,
            "num_hidden_layers": cfg.num_layers,
            "num_attention_heads": cfg.num_heads,
            "num_key_value_heads": cfg.num_kv_heads,
            "head_dim": cfg.head_dim_,
            "max_position_embeddings": cfg.max_seq_len,
            "rms_norm_eps": cfg.rms_eps,
            "rope_theta": cfg.rope_theta,
            "tie_word_embeddings": cfg.tie_embeddings,
            "attention_bias": cfg.attention_bias,
            "torch_dtype": "float32",
        }
        if cfg.rope_scaling is not None:
            _, factor, low_f, high_f, orig = cfg.rope_scaling
            common["rope_scaling"] = {
                "rope_type": "llama3",
                "factor": factor,
                "low_freq_factor": low_f,
                "high_freq_factor": high_f,
                "original_max_position_embeddings": orig,
            }
        if cfg.rms_offset:
            # Gemma-convention configs share the llama tensor names but
            # carry different semantics — emit a gemma config so
            # from_pretrained builds the right module.
            if cfg.hidden_act != "gelu_tanh" or not cfg.embed_scale or not cfg.tie_embeddings:
                raise ValueError(
                    "rms_offset configs export as gemma and need the full "
                    "gemma convention: hidden_act='gelu_tanh', "
                    "embed_scale=True, tie_embeddings=True."
                )
            common.update({
                "model_type": "gemma",
                "architectures": ["GemmaForCausalLM"],
                "hidden_act": "gelu_pytorch_tanh",
                "hidden_activation": "gelu_pytorch_tanh",
            })
            return common
        if cfg.hidden_act != "silu" or cfg.embed_scale:
            raise ValueError(
                "llama export supports the silu/no-embed-scale convention or "
                "the full gemma convention (rms_offset=True); this mix is "
                "not representable as an HF architecture."
            )
        common.update({
            "model_type": "llama",
            "architectures": ["LlamaForCausalLM"],
            "hidden_act": "silu",
            "mlp_bias": False,
        })
        return common
    if family == "gpt2":
        return {
            "model_type": "gpt2",
            "architectures": ["GPT2LMHeadModel"],
            "vocab_size": cfg.vocab_size,
            "n_embd": cfg.hidden_size,
            "n_layer": cfg.num_layers,
            "n_head": cfg.num_heads,
            "n_positions": cfg.max_seq_len,
            "n_ctx": cfg.max_seq_len,
            "n_inner": int(params["layers"]["w_up"].shape[-1]),
            "layer_norm_epsilon": cfg.layer_norm_eps,
            "activation_function": "gelu_new",
            "torch_dtype": "float32",
        }
    if family == "bert":
        return {
            "model_type": "bert",
            "architectures": ["BertForSequenceClassification"],
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.hidden_size,
            "num_hidden_layers": cfg.num_layers,
            "num_attention_heads": cfg.num_heads,
            "intermediate_size": int(params["layers"]["w_up"].shape[-1]),
            "max_position_embeddings": cfg.max_seq_len,
            "type_vocab_size": cfg.type_vocab_size,
            "layer_norm_eps": cfg.layer_norm_eps,
            "num_labels": cfg.num_labels,
            "id2label": {str(i): f"LABEL_{i}" for i in range(cfg.num_labels)},
            "label2id": {f"LABEL_{i}": i for i in range(cfg.num_labels)},
            "hidden_act": "gelu",
            "torch_dtype": "float32",
        }
    if family == "t5":
        return {
            "model_type": "t5",
            "architectures": ["T5ForConditionalGeneration"],
            "vocab_size": cfg.vocab_size,
            "d_model": cfg.hidden_size,
            "d_kv": cfg.head_dim,
            "d_ff": cfg.intermediate_size,
            "num_layers": cfg.num_layers,
            "num_decoder_layers": cfg.num_layers,
            "num_heads": cfg.num_heads,
            "relative_attention_num_buckets": cfg.num_buckets,
            "relative_attention_max_distance": cfg.max_distance,
            "layer_norm_epsilon": cfg.rms_eps,
            "feed_forward_proj": "relu",
            "tie_word_embeddings": True,
            "is_encoder_decoder": True,
            "torch_dtype": "float32",
        }
    if family == "mixtral":
        return {
            "model_type": "mixtral",
            "architectures": ["MixtralForCausalLM"],
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.hidden_size,
            "intermediate_size": cfg.intermediate_size,
            "num_hidden_layers": cfg.num_layers,
            "num_attention_heads": cfg.num_heads,
            "num_key_value_heads": cfg.num_kv_heads,
            "num_local_experts": cfg.num_experts,
            "num_experts_per_tok": cfg.top_k,
            "max_position_embeddings": cfg.max_seq_len,
            "rms_norm_eps": cfg.rms_eps,
            "rope_theta": cfg.rope_theta,
            "tie_word_embeddings": False,
            "torch_dtype": "float32",
        }
    if family == "resnet":
        e = 4 if cfg.block == "bottleneck" else 1
        return {
            "model_type": "resnet",
            "architectures": ["ResNetForImageClassification"],
            "num_channels": cfg.num_channels,
            "embedding_size": cfg.width,
            "hidden_sizes": [
                cfg.width * (2**s) * e for s in range(len(cfg.stage_sizes))
            ],
            "depths": list(cfg.stage_sizes),
            "layer_type": cfg.block,
            "downsample_in_first_stage": False,
            "num_labels": cfg.num_labels,
            "id2label": {str(i): f"LABEL_{i}" for i in range(cfg.num_labels)},
            "label2id": {f"LABEL_{i}": i for i in range(cfg.num_labels)},
            "hidden_act": "relu",
            "torch_dtype": "float32",
        }
    # vit
    return {
        "model_type": "vit",
        "architectures": ["ViTForImageClassification"],
        "image_size": cfg.image_size,
        "patch_size": cfg.patch_size,
        "num_channels": cfg.num_channels,
        "hidden_size": cfg.hidden_size,
        "num_hidden_layers": cfg.num_layers,
        "num_attention_heads": cfg.num_heads,
        "intermediate_size": int(params["layers"]["w_up"].shape[-1]),
        "layer_norm_eps": cfg.layer_norm_eps,
        "num_labels": cfg.num_labels,
        "id2label": {str(i): f"LABEL_{i}" for i in range(cfg.num_labels)},
        "label2id": {f"LABEL_{i}": i for i in range(cfg.num_labels)},
        "hidden_act": "gelu",
        "torch_dtype": "float32",
    }


def export_state_dict(family: str, params: dict, config) -> dict:
    """Native param tree -> transformers-style numpy state dict."""
    if family not in _EXPORTERS:
        raise ValueError(
            f"Export supports {sorted(_EXPORTERS)}; got {family!r}"
        )
    return _EXPORTERS[family](params, config)


def export_hf_checkpoint(family: str, params: dict, config, path: str) -> str:
    """Write ``config.json`` + ``model.safetensors`` that transformers
    ``from_pretrained(path)`` loads.  Returns ``path``."""
    from safetensors.numpy import save_file

    os.makedirs(path, exist_ok=True)
    sd = export_state_dict(family, params, config)
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(_hf_config_dict(family, config, params), f, indent=2)
    # metadata format key: older transformers releases reject safetensors
    # files without it.
    save_file(sd, os.path.join(path, "model.safetensors"), metadata={"format": "pt"})
    return path
