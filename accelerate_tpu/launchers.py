"""In-process / multi-process launchers.

Parity target: reference ``src/accelerate/launchers.py`` (301 LoC):
``notebook_launcher`` (40-265), ``debug_launcher`` (268-301).

TPU-native redesign: JAX runs ONE process per host, so ``notebook_launcher`` on a
TPU host simply calls the function (no ``xmp.spawn`` fan-out — the mesh covers the
local chips).  ``debug_launcher`` spawns N OS processes that form a REAL
``jax.distributed`` cluster over localhost CPU devices — the replacement for the
reference's gloo-based CPU simulation (SURVEY §4), exercising the true multi-host
code paths (collectives, barriers, per-process data shards) without TPUs.
"""

from __future__ import annotations

import os
import socket
import traceback
from typing import Callable

from .utils.environment import patch_environment

__all__ = ["notebook_launcher", "debug_launcher"]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def notebook_launcher(
    function: Callable,
    args=(),
    num_processes: int = None,
    mixed_precision: str = "no",
    use_port: str = "29500",
    master_addr: str = "127.0.0.1",
    node_rank: int = 0,
    num_nodes: int = 1,
    rdzv_backend: str = "static",
    rdzv_endpoint: str = "",
    rdzv_conf=None,
    rdzv_id: str = "none",
    max_restarts: int = 0,
    monitor_interval: float = 0.1,
    log_line_prefix_template=None,
):
    """Launch training from a notebook.

    On a TPU host this is a direct call (one process drives all local chips via
    the mesh — the reference needed ``xmp.spawn`` because torch_xla used one
    process per core).  ``num_processes > 1`` on CPU delegates to the
    multi-process CPU cluster of `debug_launcher`.
    """
    import jax

    from .state import honor_cpu_platform_env

    honor_cpu_platform_env()
    platform = jax.default_backend()
    if platform in ("tpu", "axon") or not num_processes or num_processes <= 1:
        # Elastic retry (reference ``notebook_launcher(max_restarts=...)`` →
        # torchelastic): re-invoke the function on failure up to max_restarts
        # times.  JAX state is process-global, so restarts reuse the backend.
        attempts = max(int(max_restarts), 0) + 1
        last_exc = None
        for attempt in range(attempts):
            try:
                with patch_environment(ACCELERATE_MIXED_PRECISION=mixed_precision):
                    return function(*args)
            except Exception as exc:  # noqa: BLE001 — elastic restart boundary
                last_exc = exc
                if attempt + 1 < attempts:
                    import logging

                    logging.getLogger(__name__).warning(
                        "notebook_launcher attempt %d/%d failed (%s); restarting",
                        attempt + 1, attempts, exc,
                    )
        raise last_exc
    # Multi-process path: same elastic semantics — each restart re-forms the
    # whole worker cluster (torchelastic restarts the full group too).
    attempts = max(int(max_restarts), 0) + 1
    last_exc = None
    for attempt in range(attempts):
        try:
            return debug_launcher(function, args=args, num_processes=num_processes)
        except Exception as exc:  # noqa: BLE001 — elastic restart boundary
            last_exc = exc
            if attempt + 1 < attempts:
                import logging

                logging.getLogger(__name__).warning(
                    "notebook_launcher cluster attempt %d/%d failed (%s); restarting",
                    attempt + 1, attempts, exc,
                )
    raise last_exc


def _worker_entry(fn, args, env: dict, rank: int, queue):
    try:
        os.environ.update(env)
        os.environ["ACCELERATE_PROCESS_ID"] = str(rank)
        # Fresh backend in the child with CPU platform.
        import jax

        jax.config.update("jax_platforms", "cpu")
        fn(*args)
        queue.put((rank, None))
    except Exception:
        queue.put((rank, traceback.format_exc()))


def debug_launcher(function: Callable, args=(), num_processes: int = 2):
    """Run ``function`` in ``num_processes`` real JAX processes on localhost CPU.

    Parity: reference ``debug_launcher`` (``launchers.py:268-301``) which forked N
    gloo CPU workers.  Here each worker joins a ``jax.distributed`` cluster
    (coordinator = process 0), so cross-process collectives, barriers and
    dataloader shards behave exactly as on a multi-host TPU pod.
    """
    import multiprocessing

    ctx = multiprocessing.get_context("spawn")
    port = _free_port()
    env = {
        "JAX_PLATFORMS": "cpu",
        "ACCELERATE_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "ACCELERATE_NUM_PROCESSES": str(num_processes),
        "ACCELERATE_DEBUG_LAUNCHER": "1",
        # Keep the virtual-device override out of children: 1 CPU device per proc.
        "XLA_FLAGS": os.environ.get("XLA_FLAGS", "").replace(
            "--xla_force_host_platform_device_count=8", ""
        ),
    }
    import queue as queue_mod

    queue = ctx.Queue()
    procs = []
    for rank in range(num_processes):
        p = ctx.Process(target=_worker_entry, args=(function, args, env, rank, queue))
        p.start()
        procs.append(p)
    failures = []
    reported = 0
    # Poll with a timeout so a worker that dies before reporting (segfault,
    # SIGKILL) is detected via its exit code instead of hanging the parent.
    while reported < num_processes:
        try:
            rank, err = queue.get(timeout=5)
            reported += 1
            if err is not None:
                failures.append((rank, err))
        except queue_mod.Empty:
            dead = [
                (i, p.exitcode) for i, p in enumerate(procs) if not p.is_alive() and p.exitcode != 0
            ]
            if dead:
                for r, code in dead:
                    failures.append((r, f"worker exited with code {code} before reporting"))
                break
    for p in procs:
        p.join(timeout=30)
        if p.is_alive():
            p.terminate()
    if failures:
        details = "\n".join(f"--- rank {r} ---\n{e}" for r, e in failures)
        raise RuntimeError(f"debug_launcher workers failed:\n{details}")
