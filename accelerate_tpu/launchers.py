"""In-process / multi-process launchers + the fleet supervisor.

Parity target: reference ``src/accelerate/launchers.py`` (301 LoC):
``notebook_launcher`` (40-265), ``debug_launcher`` (268-301).

TPU-native redesign: JAX runs ONE process per host, so ``notebook_launcher`` on a
TPU host simply calls the function (no ``xmp.spawn`` fan-out — the mesh covers the
local chips).  ``debug_launcher`` spawns N OS processes that form a REAL
``jax.distributed`` cluster over localhost CPU devices — the replacement for the
reference's gloo-based CPU simulation (SURVEY §4), exercising the true multi-host
code paths (collectives, barriers, per-process data shards) without TPUs.

:class:`FleetSupervisor` is the parent-side half of the hardened fleet runtime
(worker-side primitives live in ``resilience/fleet.py``): it owns the env
contract for every worker it spawns, watches child exits AND per-rank step-loop
heartbeats, tears the fleet down within a bounded grace window when a member
dies or wedges (survivors would otherwise hang forever in their next
collective), harvests every rank's flight-recorder stream into one fleet
postmortem, and — in elastic mode — relaunches at the reduced world size so
elastic resume can pick the run back up.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import threading
import time
import traceback
from typing import Callable, Optional

from .utils.environment import patch_environment

__all__ = ["notebook_launcher", "debug_launcher", "FleetSupervisor"]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def notebook_launcher(
    function: Callable,
    args=(),
    num_processes: int = None,
    mixed_precision: str = "no",
    use_port: str = "29500",
    master_addr: str = "127.0.0.1",
    node_rank: int = 0,
    num_nodes: int = 1,
    rdzv_backend: str = "static",
    rdzv_endpoint: str = "",
    rdzv_conf=None,
    rdzv_id: str = "none",
    max_restarts: int = 0,
    monitor_interval: float = 0.1,
    log_line_prefix_template=None,
):
    """Launch training from a notebook.

    On a TPU host this is a direct call (one process drives all local chips via
    the mesh — the reference needed ``xmp.spawn`` because torch_xla used one
    process per core).  ``num_processes > 1`` on CPU delegates to the
    multi-process CPU cluster of `debug_launcher`.
    """
    import jax

    from .state import honor_cpu_platform_env

    honor_cpu_platform_env()
    platform = jax.default_backend()
    if platform in ("tpu", "axon") or not num_processes or num_processes <= 1:
        # Elastic retry (reference ``notebook_launcher(max_restarts=...)`` →
        # torchelastic): re-invoke the function on failure up to max_restarts
        # times.  JAX state is process-global, so restarts reuse the backend.
        attempts = max(int(max_restarts), 0) + 1
        last_exc = None
        for attempt in range(attempts):
            try:
                with patch_environment(ACCELERATE_MIXED_PRECISION=mixed_precision):
                    return function(*args)
            except Exception as exc:  # noqa: BLE001 — elastic restart boundary
                last_exc = exc
                _flush_flight_recorder("notebook_launcher_exception", error=traceback.format_exc())
                if attempt + 1 < attempts:
                    import logging

                    logging.getLogger(__name__).warning(
                        "notebook_launcher attempt %d/%d failed (%s); restarting",
                        attempt + 1, attempts, exc,
                    )
        raise last_exc
    # Multi-process path: same elastic semantics — each restart re-forms the
    # whole worker cluster (torchelastic restarts the full group too).
    attempts = max(int(max_restarts), 0) + 1
    last_exc = None
    for attempt in range(attempts):
        try:
            return debug_launcher(function, args=args, num_processes=num_processes)
        except Exception as exc:  # noqa: BLE001 — elastic restart boundary
            last_exc = exc
            if attempt + 1 < attempts:
                import logging

                logging.getLogger(__name__).warning(
                    "notebook_launcher cluster attempt %d/%d failed (%s); restarting",
                    attempt + 1, attempts, exc,
                )
    raise last_exc


def _flush_flight_recorder(reason: str, error: Optional[str] = None) -> None:
    """Best-effort crash flush: a worker that dies from a Python exception is
    caught (not killed by a signal), so the flight recorder's signal/excepthook
    paths never fire — without an explicit flush its last events would die
    with the process and the fleet postmortem would show the crashed rank as
    silent."""
    try:
        from .telemetry.flightrec import get_flight_recorder

        rec = get_flight_recorder()
        if rec.enabled:
            if error is not None:
                rec.record("crash", origin=reason, error=error[-2000:])
            rec.flush(reason=reason)
    except Exception:
        pass


def _worker_entry(fn, args, env: dict, rank: int, queue):
    try:
        os.environ.update(env)
        os.environ["ACCELERATE_PROCESS_ID"] = str(rank)
        # Fresh backend in the child with CPU platform.
        import jax

        jax.config.update("jax_platforms", "cpu")
        fn(*args)
        queue.put((rank, None))
    except Exception:
        err = traceback.format_exc()
        _flush_flight_recorder("worker_exception", error=err)
        queue.put((rank, err))


def debug_launcher(function: Callable, args=(), num_processes: int = 2):
    """Run ``function`` in ``num_processes`` real JAX processes on localhost CPU.

    Parity: reference ``debug_launcher`` (``launchers.py:268-301``) which forked N
    gloo CPU workers.  Here each worker joins a ``jax.distributed`` cluster
    (coordinator = process 0), so cross-process collectives, barriers and
    dataloader shards behave exactly as on a multi-host TPU pod.
    """
    import multiprocessing

    ctx = multiprocessing.get_context("spawn")
    port = _free_port()
    env = {
        "JAX_PLATFORMS": "cpu",
        "ACCELERATE_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "ACCELERATE_NUM_PROCESSES": str(num_processes),
        "ACCELERATE_DEBUG_LAUNCHER": "1",
        # Keep the virtual-device override out of children: 1 CPU device per proc.
        "XLA_FLAGS": os.environ.get("XLA_FLAGS", "").replace(
            "--xla_force_host_platform_device_count=8", ""
        ),
    }
    import queue as queue_mod

    queue = ctx.Queue()
    procs = []
    for rank in range(num_processes):
        p = ctx.Process(target=_worker_entry, args=(function, args, env, rank, queue))
        p.start()
        procs.append(p)
    failures = []
    reported = 0
    # Poll with a timeout so a worker that dies before reporting (segfault,
    # SIGKILL) is detected via its exit code instead of hanging the parent.
    # The FIRST failure ends the wait: the dead rank's siblings are stuck in
    # their next collective and will never report — waiting on them (the old
    # behavior) hung the launcher until their own join timeout.
    while reported < num_processes and not failures:
        try:
            rank, err = queue.get(timeout=1.0)
            reported += 1
            if err is not None:
                failures.append((rank, err))
        except queue_mod.Empty:
            dead = [
                (i, p.exitcode) for i, p in enumerate(procs) if not p.is_alive() and p.exitcode != 0
            ]
            for r, code in dead:
                failures.append((r, f"worker exited with code {code} before reporting"))
    if failures:
        # Reap the survivors NOW: SIGTERM, a short grace, then SIGKILL for
        # anyone wedged in a dead collective (signal handlers can't run
        # while the main thread is stuck inside the runtime).
        for p in procs:
            if p.is_alive():
                p.terminate()
        for p in procs:
            p.join(timeout=10)
        for p in procs:
            if p.is_alive():
                p.kill()
                p.join(timeout=5)
        details = "\n".join(f"--- rank {r} ---\n{e}" for r, e in failures)
        raise RuntimeError(f"debug_launcher workers failed:\n{details}")
    for p in procs:
        p.join(timeout=30)
        if p.is_alive():
            p.terminate()


# ---------------------------------------------------------------------------
# FleetSupervisor — parent-side fleet runtime
# ---------------------------------------------------------------------------


class _FleetMember:
    __slots__ = ("rank", "proc", "spawned_at", "ever_beat")

    def __init__(self, rank: int, proc: subprocess.Popen):
        self.rank = rank
        self.proc = proc
        self.spawned_at = time.monotonic()
        self.ever_beat = False


class FleetSupervisor:
    """Spawn and babysit an N-process ``jax.distributed`` fleet.

    ``spawn(rank, world_size, env)`` must start one worker and return its
    ``subprocess.Popen``; the supervisor owns the env contract (coordinator
    address on a fresh port per attempt, world size, rank, heartbeat dir) and
    the caller merges in whatever else the workers need.

    Liveness has two signals:

    - **child exit** — any nonzero exit marks the fleet ``worker_dead``;
    - **heartbeat stall** — workers that opt in (anything driving
      ``Accelerator.check_preemption``, via ``resilience.fleet.maybe_beat``)
      beat a per-rank file from their step loop; a rank whose file goes stale
      for ``heartbeat_timeout_s`` marks the fleet ``wedged``.  With
      ``require_heartbeat=True`` a rank that never beats at all is judged on
      the same clock (for fleets known to be instrumented).

    Either way the survivors are torn down within ``grace_s`` (SIGTERM, then
    SIGKILL — a process stuck inside a dead collective never runs its Python
    signal handler), every rank's flight-recorder/telemetry stream under
    ``telemetry_dir`` is merged into one ``fleet_postmortem_a<N>.json``, and —
    when ``elastic=True`` — the fleet relaunches at world size N-1 (down to
    ``min_processes``), where elastic resume restores the run.

    SIGTERM/SIGINT delivered to the supervisor itself are forwarded to every
    worker (coordinated drain: the workers' ``PreemptionGuard`` agrees on one
    final checkpoint); workers then get ``drain_grace_s`` to exit cleanly.
    """

    def __init__(
        self,
        spawn: Callable[[int, int, dict], subprocess.Popen],
        num_processes: int,
        workdir: str,
        *,
        heartbeat_timeout_s: float = 60.0,
        grace_s: float = 10.0,
        drain_grace_s: float = 60.0,
        poll_s: float = 0.2,
        elastic: bool = False,
        min_processes: int = 1,
        require_heartbeat: bool = False,
        telemetry_dir: Optional[str] = None,
        host: str = "127.0.0.1",
    ):
        if num_processes < 1:
            raise ValueError(f"num_processes must be >= 1, got {num_processes}")
        self.spawn = spawn
        self.num_processes = num_processes
        self.workdir = workdir
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.grace_s = grace_s
        self.drain_grace_s = drain_grace_s
        self.poll_s = poll_s
        self.elastic = elastic
        self.min_processes = max(1, min_processes)
        self.require_heartbeat = require_heartbeat
        self.telemetry_dir = telemetry_dir
        self.host = host
        self._drain_signum: Optional[int] = None
        os.makedirs(workdir, exist_ok=True)

    # -- signal plumbing (drain forwarding) ---------------------------------

    def _install_drain_handler(self):
        if threading.current_thread() is not threading.main_thread():
            return None
        previous = {}

        def _handler(signum, frame):
            self._drain_signum = signum

        for signum in (signal.SIGTERM, signal.SIGINT):
            try:
                previous[signum] = signal.signal(signum, _handler)
            except (ValueError, OSError):
                pass
        return previous

    @staticmethod
    def _restore_handlers(previous):
        if not previous:
            return
        for signum, handler in previous.items():
            try:
                signal.signal(signum, handler)
            except (ValueError, OSError, TypeError):
                pass

    # -- lifecycle ----------------------------------------------------------

    def run(self) -> dict:
        """Supervise until the fleet completes, drains, or dies unrecoverably.
        Returns a summary: ``verdict`` (``completed`` / ``drained`` /
        ``worker_dead`` / ``wedged`` / ``drain_timeout``), final
        ``world_size``, per-``attempts`` records, and the last postmortem
        path (None when no failure produced one)."""
        previous = self._install_drain_handler()
        attempts = []
        world = self.num_processes
        try:
            while True:
                attempt = self._run_attempt(world, len(attempts))
                attempts.append(attempt)
                if attempt["verdict"] in ("completed", "drained", "drain_timeout"):
                    break
                relaunch = (
                    self.elastic
                    and attempt["verdict"] in ("worker_dead", "wedged")
                    and world - 1 >= self.min_processes
                )
                if not relaunch:
                    break
                world -= 1
                self._note_event(
                    "fleet.relaunch", world_size=world, cause=attempt["verdict"]
                )
                self._inc_counter("fleet.elastic_restarts")
        finally:
            self._restore_handlers(previous)
        postmortems = [a["postmortem"] for a in attempts if a.get("postmortem")]
        return {
            "verdict": attempts[-1]["verdict"],
            "world_size": world,
            "attempts": attempts,
            "postmortem": postmortems[-1] if postmortems else None,
        }

    def _run_attempt(self, world: int, index: int) -> dict:
        from .resilience.fleet import heartbeat_path

        attempt_dir = os.path.join(self.workdir, f"attempt{index}")
        hb_dir = os.path.join(attempt_dir, "heartbeats")
        os.makedirs(hb_dir, exist_ok=True)
        port = _free_port()
        members = []
        start = time.monotonic()
        for rank in range(world):
            env = {
                "ACCELERATE_COORDINATOR_ADDRESS": f"{self.host}:{port}",
                "ACCELERATE_NUM_PROCESSES": str(world),
                "ACCELERATE_PROCESS_ID": str(rank),
                "ACCELERATE_TPU_HEARTBEAT_DIR": hb_dir,
                "ACCELERATE_FLEET_ATTEMPT": str(index),
            }
            members.append(_FleetMember(rank, self.spawn(rank, world, env)))

        verdict = None
        dead_rank = None
        wedged_rank = None
        exit_code = None
        drain_forwarded_at = None
        while verdict is None:
            codes = [m.proc.poll() for m in members]
            failed = [
                (m.rank, rc) for m, rc in zip(members, codes) if rc not in (None, 0)
            ]
            if failed:
                dead_rank, exit_code = failed[0]
                verdict = "worker_dead"
                break
            if all(rc == 0 for rc in codes):
                verdict = "drained" if drain_forwarded_at is not None else "completed"
                break
            wedged_rank = self._stalest_rank(members, hb_dir, heartbeat_path)
            if wedged_rank is not None:
                verdict = "wedged"
                break
            if self._drain_signum is not None:
                if drain_forwarded_at is None:
                    drain_forwarded_at = time.monotonic()
                    self._note_event(
                        "fleet.drain", signum=int(self._drain_signum), world_size=world
                    )
                    for m in members:
                        if m.proc.poll() is None:
                            try:
                                m.proc.send_signal(self._drain_signum)
                            except OSError:
                                pass
                elif time.monotonic() - drain_forwarded_at > self.drain_grace_s:
                    verdict = "drain_timeout"
                    break
            time.sleep(self.poll_s)

        teardown_s = 0.0
        postmortem = None
        if verdict in ("worker_dead", "wedged", "drain_timeout"):
            teardown_s = self._teardown(members)
            postmortem = self._harvest_postmortem(
                index, world, verdict, dead_rank, wedged_rank, exit_code
            )
            if verdict == "worker_dead":
                self._inc_counter("fleet.worker_deaths")
                self._note_event(
                    "fleet.worker_dead", rank=dead_rank, exit_code=exit_code,
                    world_size=world, teardown_s=round(teardown_s, 3),
                )
            elif verdict == "wedged":
                self._inc_counter("fleet.wedged_workers")
                self._note_event(
                    "fleet.wedged", rank=wedged_rank, world_size=world,
                    heartbeat_timeout_s=self.heartbeat_timeout_s,
                    teardown_s=round(teardown_s, 3),
                )
        exit_codes = {m.rank: m.proc.poll() for m in members}
        return {
            "attempt": index,
            "world_size": world,
            "verdict": verdict,
            "dead_rank": dead_rank,
            "wedged_rank": wedged_rank,
            "exit_code": exit_code,
            "exit_codes": exit_codes,
            "teardown_s": round(teardown_s, 3),
            "duration_s": round(time.monotonic() - start, 3),
            "postmortem": postmortem,
            "heartbeat_dir": hb_dir,
        }

    def _stalest_rank(self, members, hb_dir, heartbeat_path) -> Optional[int]:
        """The first live rank whose heartbeat went stale (None when all
        fresh).  Ranks that never beat are only judged under
        ``require_heartbeat`` — an uninstrumented script must not read as
        wedged."""
        now = time.time()
        mono_now = time.monotonic()
        for m in members:
            if m.proc.poll() is not None:
                continue
            path = heartbeat_path(hb_dir, m.rank)
            try:
                age = now - os.stat(path).st_mtime
                m.ever_beat = True
            except OSError:
                if not self.require_heartbeat:
                    continue
                age = mono_now - m.spawned_at
            if age > self.heartbeat_timeout_s:
                return m.rank
        return None

    def _teardown(self, members) -> float:
        """Bounded teardown of every live member: SIGTERM, ``grace_s`` to
        comply, then SIGKILL — survivors of a dead collective are wedged in
        the runtime and never see the SIGTERM."""
        t0 = time.monotonic()
        for m in members:
            if m.proc.poll() is None:
                try:
                    m.proc.terminate()
                except OSError:
                    pass
        deadline = t0 + self.grace_s
        while time.monotonic() < deadline and any(
            m.proc.poll() is None for m in members
        ):
            time.sleep(min(self.poll_s, 0.1))
        for m in members:
            if m.proc.poll() is None:
                try:
                    m.proc.kill()
                except OSError:
                    pass
        for m in members:
            try:
                m.proc.wait(timeout=10)
            except (subprocess.TimeoutExpired, OSError):
                pass
        self._note_event(
            "fleet.teardown", grace_s=self.grace_s,
            took_s=round(time.monotonic() - t0, 3),
        )
        return time.monotonic() - t0

    def _harvest_postmortem(
        self, index, world, verdict, dead_rank, wedged_rank, exit_code
    ) -> Optional[str]:
        """Merge every rank's flight-recorder/telemetry stream into one
        rank-tagged postmortem document (the ``telemetry.report --fleet``
        view, persisted) so the blame trail survives the fleet."""
        if not self.telemetry_dir or not os.path.isdir(self.telemetry_dir):
            return None
        try:
            from .telemetry.report import load_fleet_records, summarize_fleet

            summary = summarize_fleet(load_fleet_records(self.telemetry_dir))
            doc = {
                "cause": verdict,
                "dead_rank": dead_rank,
                "wedged_rank": wedged_rank,
                "exit_code": exit_code,
                "world_size": world,
                "attempt": index,
                "t": time.time(),
                "fleet": summary,
            }
            path = os.path.join(self.workdir, f"fleet_postmortem_a{index}.json")
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=2, default=str)
            os.replace(tmp, path)
            self._note_event("fleet.postmortem", path=path, cause=verdict)
            return path
        except Exception:
            import logging

            logging.getLogger(__name__).exception("fleet postmortem harvest failed")
            return None

    # -- telemetry (best-effort; the supervisor may run with it disabled) ----

    @staticmethod
    def _note_event(name, **fields):
        try:
            from .telemetry import get_telemetry

            tel = get_telemetry()
            if tel.enabled:
                tel.event(name, **fields)
        except Exception:
            pass

    @staticmethod
    def _inc_counter(name):
        try:
            from .telemetry import get_telemetry

            tel = get_telemetry()
            if tel.enabled:
                tel.registry.counter(name).inc()
        except Exception:
            pass
