"""Big-model inference — L6: models larger than one device's HBM.

Parity target: reference ``src/accelerate/big_modeling.py`` (749 LoC):
``init_empty_weights``/``init_on_device`` (61-170), ``cpu_offload``/``disk_offload``
(173-306), ``dispatch_model`` (309-509), ``load_checkpoint_and_dispatch`` (512+).

TPU-native design (SURVEY §2.6 north star): the tier ladder is HBM → host RAM →
disk.  ``infer_auto_device_map`` plans against the HBM budget;
``dispatch_model`` attaches `AlignDevicesHook`s that stage host/disk-resident
blocks just-in-time; execution reaches the TPU through the jit bridge, which
device_puts the staged block (the reference moved CUDA tensors per block instead,
``hooks.py:328-371``).
"""

from __future__ import annotations

import contextlib
import os
from typing import Optional, Union

from .hooks import (
    CpuOffload,
    UserCpuOffloadHook,
    add_hook_to_module,
    attach_align_device_hook,
    attach_align_device_hook_on_blocks,
)
from .utils.modeling import (
    check_device_map,
    get_balanced_memory,
    infer_auto_device_map,
    load_checkpoint_in_model,
)
from .utils.offload import OffloadedWeightsLoader, offload_state_dict


def _tensor_to_numpy(t):
    """torch tensor -> numpy, handling bfloat16 (no native numpy dtype) via the
    ml_dtypes bit-pattern view."""
    import numpy as np

    try:
        import torch
    except ImportError:
        return np.asarray(t)
    if isinstance(t, torch.Tensor):
        t = t.detach().cpu()
        if t.dtype == torch.bfloat16:
            import ml_dtypes

            return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
        return t.numpy()
    return np.asarray(t)

__all__ = [
    "init_empty_weights",
    "init_on_device",
    "cpu_offload",
    "cpu_offload_with_hook",
    "disk_offload",
    "dispatch_model",
    "load_checkpoint_and_dispatch",
    "infer_auto_device_map",
    "load_checkpoint_in_model",
]


@contextlib.contextmanager
def init_empty_weights(include_buffers: bool = False):
    """Create model parameters on the meta device — O(0) memory skeleton.

    Parity: reference ``big_modeling.py:61-110``.
    """
    with init_on_device("meta", include_buffers=include_buffers) as f:
        yield f


@contextlib.contextmanager
def init_on_device(device, include_buffers: bool = False):
    """Parity: reference ``big_modeling.py:113-170`` — patch
    ``nn.Module.register_parameter``/``register_buffer`` during construction."""
    import torch

    device = torch.device(device)
    old_register_parameter = torch.nn.Module.register_parameter
    old_register_buffer = torch.nn.Module.register_buffer

    def register_empty_parameter(module, name, param):
        old_register_parameter(module, name, param)
        if param is not None:
            param_cls = type(module._parameters[name])
            kwargs = module._parameters[name].__dict__
            kwargs["requires_grad"] = param.requires_grad
            module._parameters[name] = param_cls(
                module._parameters[name].to(device), **{k: v for k, v in kwargs.items() if k == "requires_grad"}
            )

    def register_empty_buffer(module, name, buffer, persistent=True):
        old_register_buffer(module, name, buffer, persistent=persistent)
        if buffer is not None:
            module._buffers[name] = module._buffers[name].to(device)

    try:
        torch.nn.Module.register_parameter = register_empty_parameter
        if include_buffers:
            torch.nn.Module.register_buffer = register_empty_buffer
        yield device
    finally:
        torch.nn.Module.register_parameter = old_register_parameter
        if include_buffers:
            torch.nn.Module.register_buffer = old_register_buffer


def _dedup_state_dict(model, convert) -> dict:
    """name -> converted tensor, converting each underlying storage ONCE so
    tied weights do not duplicate host RAM (same rule as dispatch_model)."""
    converted: dict[int, object] = {}
    out = {}
    for n, p in model.state_dict(keep_vars=True).items():
        if _on_meta(p):
            continue
        key = id(p)
        if key not in converted:
            converted[key] = convert(p)
        out[n] = converted[key]
    return out


def cpu_offload(model, execution_device=None, offload_buffers: bool = False, state_dict=None,
                preload_module_classes=None):
    """Whole-model CPU offload (reference ``big_modeling.py:173``): weights live in
    a host state dict, staged per-submodule at forward."""
    if state_dict is None:
        state_dict = _dedup_state_dict(model, lambda p: p.detach().cpu())
    attach_align_device_hook(
        model,
        execution_device=execution_device or "cpu",
        offload=True,
        weights_map=state_dict,
        offload_buffers=offload_buffers,
        tied_params_map={},
        tied_names=_tied_name_map(model),
        preload_module_classes=preload_module_classes,
    )
    return model


def cpu_offload_with_hook(model, execution_device=None, prev_module_hook: Optional[UserCpuOffloadHook] = None):
    """Reference ``big_modeling.py cpu_offload_with_hook`` — for sequential
    pipelines that re-use modules."""
    hook = CpuOffload(execution_device=execution_device, prev_module_hook=prev_module_hook)
    add_hook_to_module(model, hook, append=True)
    user_hook = UserCpuOffloadHook(model, hook)
    return model, user_hook


def disk_offload(model, offload_dir: str, execution_device=None, offload_buffers: bool = False,
                 preload_module_classes=None):
    """Whole-model disk offload (reference ``big_modeling.py:239``)."""
    os.makedirs(offload_dir, exist_ok=True)
    offload_state_dict(offload_dir, _dedup_state_dict(model, _tensor_to_numpy))
    weights_map = OffloadedWeightsLoader(save_folder=offload_dir)
    attach_align_device_hook(
        model,
        execution_device=execution_device or "cpu",
        offload=True,
        weights_map=weights_map,
        offload_buffers=offload_buffers,
        tied_params_map={},
        tied_names=_tied_name_map(model),
        preload_module_classes=preload_module_classes,
    )
    return model


def _tied_name_map(model) -> dict:
    """full weight name -> canonical group name, for tied-parameter dedup."""
    from .utils.modeling import find_tied_parameters

    return {n: group[0] for group in find_tied_parameters(model) for n in group}


def dispatch_model(
    model,
    device_map: dict,
    main_device=None,
    state_dict=None,
    offload_dir: Optional[str] = None,
    offload_index: Optional[dict] = None,
    offload_buffers: bool = False,
    skip_keys=None,
    preload_module_classes=None,
    force_hooks: bool = False,
):
    """Attach tier-staging hooks per device-map block (reference
    ``big_modeling.py:309-509``).

    Tiers: "tpu" blocks stay host-resident and are device_put by the jit bridge
    each call (resident in HBM between calls once prepared); "cpu" blocks stage
    from a host state dict; "disk" blocks stage from the offload folder.
    """
    check_device_map(model, device_map)

    disk_modules = [name for name, tier in device_map.items() if tier == "disk"]
    cpu_modules = [name for name, tier in device_map.items() if tier == "cpu"]

    if disk_modules and offload_dir is None and offload_index is None:
        raise ValueError(
            f"Disk-offloaded modules {disk_modules} need an `offload_dir`."
        )

    weights_map = None
    if disk_modules or cpu_modules:
        if state_dict is None:
            # Tied parameters convert ONCE: state_dict() lists each tied weight
            # under every name, and a per-name numpy conversion would duplicate
            # the host RAM the offload tier exists to save.
            state_dict = _dedup_state_dict(model, _tensor_to_numpy)
        if disk_modules and offload_dir is not None:
            disk_sd = {
                n: v
                for n, v in state_dict.items()
                if any(m == "" or n == m or n.startswith(m + ".") for m in disk_modules)
            }
            if disk_sd:
                os.makedirs(offload_dir, exist_ok=True)
                offload_state_dict(offload_dir, disk_sd)
                # Disk-tier weights must not stay pinned in host RAM — the whole
                # point of the tier (the loader falls back to the .dat files).
                state_dict = {n: v for n, v in state_dict.items() if n not in disk_sd}
        weights_map = OffloadedWeightsLoader(state_dict=state_dict, save_folder=offload_dir)

    # Every tier stages on host ("cpu"): "tpu" blocks are host-resident too — the
    # HBM transfer happens in the jit bridge, not via torch .to() (there is no
    # torch "tpu" device).
    execution_device = {name: "cpu" for name in device_map}
    offload = {name: tier in ("cpu", "disk") for name, tier in device_map.items()}

    # Tied-parameter dedup (reference big_modeling.py:410-424): one shared map
    # so a weight tied across modules materializes ONCE per staging device —
    # keyed by the group's canonical name (our weights_map is name-addressed;
    # the reference keys by data_ptr because its map is tensor-addressed).
    tied_params_map: dict = {}
    tied_names = _tied_name_map(model)

    attach_align_device_hook_on_blocks(
        model,
        execution_device=execution_device,
        offload=offload,
        weights_map=weights_map,
        offload_buffers=offload_buffers,
        skip_keys=skip_keys,
        tied_params_map=tied_params_map,
        tied_names=tied_names,
        preload_module_classes=preload_module_classes,
    )
    if weights_map is not None:
        from .hooks import wire_sequential_prefetch

        wire_sequential_prefetch(model)
    model.hf_device_map = device_map
    # Poison .to() like the reference (big_modeling.py:489-507).
    if any(tier in ("cpu", "disk") for tier in device_map.values()):
        model._original_to = model.to

        def _blocked_to(*args, **kwargs):
            raise RuntimeError(
                "You can't move a model that has been dispatched with a device map; "
                "remove the hooks first (remove_hook_from_submodules)."
            )

        model.to = _blocked_to
    return model


def _on_meta(t) -> bool:
    return hasattr(t, "device") and str(getattr(t, "device", "")) == "meta"


def load_checkpoint_and_dispatch(
    model,
    checkpoint: str,
    device_map: Optional[Union[str, dict]] = None,
    max_memory: Optional[dict] = None,
    no_split_module_classes: Optional[list] = None,
    offload_folder: Optional[str] = None,
    offload_buffers: bool = False,
    dtype=None,
    offload_state_dict: Optional[bool] = None,
    skip_keys=None,
    preload_module_classes=None,
    force_hooks: bool = False,
    strict: bool = False,
    full_state_dict: bool = True,
    broadcast_from_rank0: bool = False,
):
    """One-call load + plan + dispatch (reference ``big_modeling.py:512``)."""
    if isinstance(device_map, str):
        if device_map not in ("auto", "balanced", "balanced_low_0", "sequential"):
            raise ValueError(
                "If passed as a string, device_map must be 'auto', 'balanced', "
                "'balanced_low_0' or 'sequential'."
            )
        if device_map != "sequential":
            max_memory = get_balanced_memory(
                model,
                max_memory=max_memory,
                no_split_module_classes=no_split_module_classes,
                dtype=dtype,
                low_zero=(device_map == "balanced_low_0"),
            )
        device_map = infer_auto_device_map(
            model, max_memory=max_memory, no_split_module_classes=no_split_module_classes, dtype=dtype
        )
    load_checkpoint_in_model(
        model,
        checkpoint,
        device_map=device_map,
        offload_folder=offload_folder,
        dtype=dtype,
        strict=strict,
        full_state_dict=full_state_dict,
        broadcast_from_rank0=broadcast_from_rank0,
    )
    if device_map is None:
        return model
    return dispatch_model(
        model,
        device_map=device_map,
        offload_dir=offload_folder,
        offload_buffers=offload_buffers,
        skip_keys=skip_keys,
        preload_module_classes=preload_module_classes,
        force_hooks=force_hooks,
    )
