"""Image-classification example — the repo's analog of the reference
``examples/cv_example.py`` (ResNet on the pets dataset).

Same script shape: dataloaders, ``Accelerator``, ``prepare``, train with
``accelerator.backward``, evaluate with ``gather_for_metrics``.  The model is a
small CNN on synthetic 32x32 images (no dataset download — zero egress image);
classes are separable by channel statistics so accuracy climbs fast.

Run:  python examples/cv_example.py [--mixed_precision bf16] [--cpu]
"""

import argparse

import numpy as np
import torch
from torch.optim.lr_scheduler import LambdaLR
from torch.utils.data import DataLoader

from accelerate_tpu import Accelerator
from accelerate_tpu.utils import set_seed

NUM_CLASSES = 4
IMAGE_SIZE = 32


class SmallCNN(torch.nn.Module):
    def __init__(self, num_classes=NUM_CLASSES):
        super().__init__()
        self.conv1 = torch.nn.Conv2d(3, 16, 3, padding=1)
        self.conv2 = torch.nn.Conv2d(16, 32, 3, padding=1)
        self.head = torch.nn.Linear(32, num_classes)

    def forward(self, pixels):
        x = torch.relu(self.conv1(pixels))
        x = torch.nn.functional.max_pool2d(x, 2)
        x = torch.relu(self.conv2(x))
        x = torch.nn.functional.adaptive_avg_pool2d(x, (1, 1))
        x = torch.flatten(x, 1)
        return self.head(x)


def make_dataset(n: int, seed: int):
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, NUM_CLASSES, n)
    images = rng.normal(0, 1, (n, 3, IMAGE_SIZE, IMAGE_SIZE)).astype(np.float32)
    # Class k brightens channel k%3 and adds a class-scaled gradient pattern.
    for i, k in enumerate(labels):
        images[i, k % 3] += 1.5
        images[i] += np.linspace(0, 0.5 * (k // 3 + 1), IMAGE_SIZE)[None, None, :]
    return [
        {"pixels": torch.tensor(images[i]), "labels": int(labels[i])} for i in range(n)
    ]


def collate(samples):
    return {
        "pixels": torch.stack([s["pixels"] for s in samples]),
        "labels": torch.tensor([s["labels"] for s in samples]),
    }


def training_function(config, args):
    accelerator = Accelerator(cpu=args.cpu, mixed_precision=args.mixed_precision)
    set_seed(config["seed"])
    train_dl = DataLoader(
        make_dataset(512, 0), shuffle=True, collate_fn=collate, batch_size=config["batch_size"]
    )
    eval_dl = DataLoader(make_dataset(128, 1), collate_fn=collate, batch_size=32)

    model = SmallCNN()
    optimizer = torch.optim.AdamW(model.parameters(), lr=config["lr"])
    total = config["num_epochs"] * len(train_dl)
    scheduler = LambdaLR(optimizer, lambda step: max(0.0, 1.0 - step / max(total, 1)))
    model, optimizer, train_dl, eval_dl, scheduler = accelerator.prepare(
        model, optimizer, train_dl, eval_dl, scheduler
    )

    criterion = torch.nn.CrossEntropyLoss()
    accuracy = 0.0
    for epoch in range(config["num_epochs"]):
        model.train()
        for batch in train_dl:
            loss = criterion(model(batch["pixels"]), batch["labels"])
            accelerator.backward(loss)
            optimizer.step()
            scheduler.step()
            optimizer.zero_grad()
        model.eval()
        hits, n = 0, 0
        for batch in eval_dl:
            logits = model(batch["pixels"])
            preds = torch.argmax(logits, dim=-1)
            preds, refs = accelerator.gather_for_metrics((preds, batch["labels"]))
            hits += int((preds == refs).sum())
            n += len(refs)
        accuracy = hits / max(n, 1)
        accelerator.print(f"epoch {epoch}: accuracy {accuracy:.3f}")
    return accuracy


def main():
    parser = argparse.ArgumentParser(description="Image classification example")
    parser.add_argument(
        "--mixed_precision", type=str, default=None, choices=["no", "fp16", "bf16", "fp8"]
    )
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--num_epochs", type=int, default=3)
    args = parser.parse_args()
    training_function({"lr": 3e-3, "num_epochs": args.num_epochs, "seed": 42, "batch_size": 32}, args)


if __name__ == "__main__":
    main()
