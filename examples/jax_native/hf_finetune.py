"""The full interop loop as one script: HF checkpoint in -> native
mesh-sharded fine-tune -> HF checkpoint out.

This is the reference workflow (`from_pretrained` -> train with
Accelerate -> `save_pretrained`) re-drawn TPU-first: the torch module
exists only at the endpoints (or not at all with ``--checkpoint PATH``,
which reads safetensors straight from disk); the training loop is a
single jitted step over a GSPMD mesh on the native family.

Run:  python examples/jax_native/hf_finetune.py --fsdp 4 --dp 2 --steps 10
      python examples/jax_native/hf_finetune.py --checkpoint /path/to/hf_dir
"""

import argparse
import tempfile
import time

import numpy as np

import jax
import optax

from accelerate_tpu import AcceleratorState, ParallelismConfig
from accelerate_tpu.models import gpt2, hf_export, hf_import
from accelerate_tpu.parallel.sharding import data_sharding, shard_params


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--checkpoint", default=None,
                        help="HF checkpoint dir; omit to build a tiny random GPT-2")
    parser.add_argument("--out", default=None,
                        help="export dir (default: a temp dir, printed)")
    parser.add_argument("--fsdp", type=int, default=1)
    parser.add_argument("--dp", type=int, default=1)
    parser.add_argument("--tp", type=int, default=1)
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--seq_len", type=int, default=32)
    args = parser.parse_args()

    if args.checkpoint:
        family, cfg, params = hf_import.load_hf_checkpoint(args.checkpoint)
        if family != "gpt2":
            raise SystemExit(f"this example fine-tunes gpt2; got {family}")
    else:
        # Zero-egress default: a tiny randomly initialized HF GPT-2, so the
        # import path is exercised end to end without downloading anything.
        import transformers

        hf = transformers.GPT2LMHeadModel(
            transformers.GPT2Config(vocab_size=256, n_embd=64, n_layer=2,
                                    n_head=4, n_positions=64)
        )
        family, cfg, params = hf_import.from_hf(hf)

    state = AcceleratorState(
        parallelism_config=ParallelismConfig(dp=args.dp, fsdp=args.fsdp, tp=args.tp)
    )
    mesh = state.mesh
    print(f"{family}: {cfg.num_layers}L/{cfg.hidden_size}d on mesh {dict(mesh.shape)}")
    params = shard_params(params, mesh, gpt2.param_specs(cfg))

    tx = optax.adamw(3e-4)
    opt_state = tx.init(params)

    @jax.jit
    def train_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(gpt2.loss_fn)(params, batch, cfg)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    rng = np.random.default_rng(0)
    t0 = time.perf_counter()
    loss = None
    for step in range(args.steps):
        ids = rng.integers(0, cfg.vocab_size, (args.batch_size, args.seq_len))
        batch = {"input_ids": jax.device_put(ids.astype(np.int32), data_sharding(mesh))}
        params, opt_state, loss = train_step(params, opt_state, batch)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step}: loss {float(jax.device_get(loss)):.4f}")
    dt = time.perf_counter() - t0
    print(f"{args.steps * args.batch_size * args.seq_len / dt:.0f} tokens/s (incl. compile)")

    out = args.out or tempfile.mkdtemp(prefix="hf_export_")
    hf_export.export_hf_checkpoint(family, jax.device_get(params), cfg, out)
    print(f"exported HF checkpoint -> {out} (transformers.from_pretrained loads it)")
    # --steps 0 turns the script into a pure HF->native->HF converter.
    return float(jax.device_get(loss)) if loss is not None else None


if __name__ == "__main__":
    main()
