"""JAX-native CNN example: ResNet classification over a GSPMD mesh.

The reference's canonical CV path is ``torchvision.models.resnet`` through
the model-agnostic loop with ``SyncBatchNorm`` under DDP
(``examples/cv_example.py``); this is the TPU-first equivalent on the
native ResNet family — NHWC convs on the MXU, functional batch statistics
threaded through the train step, and cross-replica batch-norm for free
(the batch axis is sharded, so ``jnp.mean`` is the global mean).

Run:  python examples/jax_native/resnet_train.py --dp 8 --steps 10
FSDP-sharded kernels:  --fsdp 4 --tp 2
"""

import argparse
import time

import numpy as np

import jax
import optax

from accelerate_tpu import AcceleratorState, ParallelismConfig
from accelerate_tpu.models import resnet
from accelerate_tpu.parallel.sharding import data_sharding, shard_params
from accelerate_tpu.utils import FullyShardedDataParallelPlugin


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--fsdp", type=int, default=1)
    parser.add_argument("--tp", type=int, default=1)
    parser.add_argument("--dp", type=int, default=1)
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--image_size", type=int, default=32)
    parser.add_argument("--width", type=int, default=8)
    parser.add_argument("--labels", type=int, default=4)
    parser.add_argument("--block", choices=("basic", "bottleneck"), default="basic")
    args = parser.parse_args()

    state = AcceleratorState(
        parallelism_config=ParallelismConfig(dp=args.dp, fsdp=args.fsdp, tp=args.tp),
        fsdp_plugin=FullyShardedDataParallelPlugin(),
    )
    mesh = state.mesh
    print(f"mesh: {dict(mesh.shape)} on {jax.device_count()} devices")

    cfg = resnet.ResNetConfig.tiny(
        block=args.block, width=args.width, num_labels=args.labels
    )
    params = resnet.init_params(cfg, jax.random.key(0))
    params = shard_params(params, mesh, resnet.param_specs(cfg))
    batch_stats = resnet.init_batch_stats(cfg)

    tx = optax.adamw(3e-3)
    opt_state = tx.init(params)

    @jax.jit
    def train_step(params, batch_stats, opt_state, batch):
        (loss, new_stats), grads = jax.value_and_grad(
            resnet.classification_loss_fn, has_aux=True
        )(params, batch_stats, batch, cfg)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_stats, opt_state, loss

    if args.steps < 1:
        raise SystemExit("--steps must be >= 1")
    rng = np.random.default_rng(0)
    t0 = time.perf_counter()
    loss = None
    for step in range(args.steps):
        # Synthetic data with a learnable rule: class shifts channel 0.
        pixels = rng.normal(size=(args.batch_size, args.image_size, args.image_size, 3))
        labels = (np.arange(args.batch_size) % cfg.num_labels).astype(np.int32)
        pixels[..., 0] += 0.5 * labels[:, None, None]
        batch = {
            "pixel_values": jax.device_put(pixels.astype(np.float32), data_sharding(mesh)),
            "labels": jax.device_put(labels, data_sharding(mesh)),
        }
        params, batch_stats, opt_state, loss = train_step(
            params, batch_stats, opt_state, batch
        )
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step}: loss {float(jax.device_get(loss)):.4f}")
    dt = time.perf_counter() - t0
    n = args.steps * args.batch_size
    print(f"{n / dt:.1f} images/s (incl. compile)")
    return float(jax.device_get(loss))


if __name__ == "__main__":
    main()
