"""JAX-native vision example: ViT classification over a GSPMD mesh.

The reference's CV examples (``examples/cv_example.py``) run torchvision
models through the model-agnostic loop; this is the TPU-first equivalent
on the native ViT family — patchify-as-matmul embedding, explicit
partition rules, one jit-compiled train step.  Runs on a single chip, a
virtual CPU mesh (``JAX_PLATFORMS=cpu`` +
``XLA_FLAGS=--xla_force_host_platform_device_count=8``), or a pod slice.

Run:  python examples/jax_native/vit_train.py --fsdp 4 --tp 2 --steps 10
Patch-sequence parallelism:  --dp 2 --sp 4 --pool mean
"""

import argparse
import time

import numpy as np

import jax
import optax

from accelerate_tpu import AcceleratorState, ParallelismConfig
from accelerate_tpu.models import vit
from accelerate_tpu.parallel.sharding import data_sharding, make_param_specs, shard_params
from accelerate_tpu.utils import FullyShardedDataParallelPlugin


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--fsdp", type=int, default=1)
    parser.add_argument("--tp", type=int, default=1)
    parser.add_argument("--sp", type=int, default=1)
    parser.add_argument("--dp", type=int, default=1)
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--image_size", type=int, default=64)
    parser.add_argument("--patch_size", type=int, default=8)
    parser.add_argument("--hidden", type=int, default=128)
    parser.add_argument("--layers", type=int, default=4)
    parser.add_argument("--labels", type=int, default=10)
    parser.add_argument(
        "--pool", choices=("cls", "mean"), default="cls",
        help="mean is required when --sp > 1 (a CLS token breaks sp divisibility)",
    )
    args = parser.parse_args()

    state = AcceleratorState(
        parallelism_config=ParallelismConfig(dp=args.dp, fsdp=args.fsdp, tp=args.tp, sp=args.sp),
        fsdp_plugin=FullyShardedDataParallelPlugin(),
    )
    mesh = state.mesh
    print(f"mesh: {dict(mesh.shape)} on {jax.device_count()} devices")

    cfg = vit.ViTConfig.tiny(
        image_size=args.image_size,
        patch_size=args.patch_size,
        hidden_size=args.hidden,
        num_layers=args.layers,
        num_labels=args.labels,
        pool=args.pool,
    )
    params = vit.init_params(cfg, jax.random.key(0))
    specs = make_param_specs(params, mesh, state.fsdp_plugin, rules=vit.PARTITION_RULES)
    params = shard_params(params, mesh, specs)

    tx = optax.adamw(3e-4)
    opt_state = tx.init(params)

    @jax.jit
    def train_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(vit.classification_loss_fn)(params, batch, cfg)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    if args.steps < 1:
        raise SystemExit("--steps must be >= 1")
    rng = np.random.default_rng(0)
    t0 = time.perf_counter()
    loss = None
    for step in range(args.steps):
        # Synthetic data with a learnable rule: label = brightness bucket.
        pixels = rng.normal(size=(args.batch_size, cfg.image_size, cfg.image_size, 3))
        labels = (
            (pixels.mean(axis=(1, 2, 3)) - pixels.mean()) > 0
        ).astype(np.int32) % cfg.num_labels
        batch = {
            "pixel_values": jax.device_put(pixels.astype(np.float32), data_sharding(mesh)),
            "labels": jax.device_put(labels, data_sharding(mesh)),
        }
        params, opt_state, loss = train_step(params, opt_state, batch)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step}: loss {float(jax.device_get(loss)):.4f}")
    dt = time.perf_counter() - t0
    n = args.steps * args.batch_size
    print(f"{n / dt:.1f} images/s (incl. compile)")
    return float(jax.device_get(loss))


if __name__ == "__main__":
    main()
