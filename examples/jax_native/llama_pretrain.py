"""JAX-native flagship example: llama pretraining over a GSPMD mesh.

No reference analog (the reference wraps torch models only) — this is the
TPU-first path: a pure-JAX model with explicit partition rules, an fsdp/tp/sp
mesh from ``ParallelismConfig``, and one jit-compiled train step.  Runs on a
single chip, a virtual CPU mesh (set ``JAX_PLATFORMS=cpu`` and
``XLA_FLAGS=--xla_force_host_platform_device_count=8``), or a real pod slice —
same script.

Run:  python examples/jax_native/llama_pretrain.py --fsdp 4 --tp 2 --steps 10
Long context:  --dp 2 --sp 4 --seq_len 4096 --sp_impl ring --attention pallas
"""

import argparse
import time

import numpy as np

import jax
import optax

from accelerate_tpu import AcceleratorState, ParallelismConfig
from accelerate_tpu.models import llama
from accelerate_tpu.parallel.sharding import data_sharding, make_param_specs, shard_params
from accelerate_tpu.utils import FullyShardedDataParallelPlugin


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--fsdp", type=int, default=1)
    parser.add_argument("--tp", type=int, default=1)
    parser.add_argument("--sp", type=int, default=1)
    parser.add_argument("--dp", type=int, default=1)
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--seq_len", type=int, default=128)
    parser.add_argument("--hidden", type=int, default=128)
    parser.add_argument("--layers", type=int, default=4)
    parser.add_argument(
        "--sp_impl", choices=("ring", "ulysses"), default="ring",
        help="sequence-parallel attention backend when --sp > 1",
    )
    parser.add_argument(
        "--attention", choices=("auto", "einsum", "flash", "pallas"), default="auto",
        help="attention implementation (pallas = fused MXU kernel; composes "
             "with --sp via the pallas-in-ring / pallas-ulysses paths)",
    )
    args = parser.parse_args()

    state = AcceleratorState(
        parallelism_config=ParallelismConfig(dp=args.dp, fsdp=args.fsdp, tp=args.tp, sp=args.sp),
        fsdp_plugin=FullyShardedDataParallelPlugin(),
    )
    mesh = state.mesh
    print(f"mesh: {dict(mesh.shape)} on {jax.device_count()} devices")

    cfg = llama.LlamaConfig.tiny(
        num_layers=args.layers,
        hidden_size=args.hidden,
        intermediate_size=2 * args.hidden,
        max_seq_len=args.seq_len,
        vocab_size=4096,
        sp_impl=args.sp_impl,
        attention_impl=args.attention,
    )
    params = llama.init_params(cfg, jax.random.key(0))
    specs = make_param_specs(params, mesh, state.fsdp_plugin, rules=llama.PARTITION_RULES)
    params = shard_params(params, mesh, specs)

    tx = optax.adamw(3e-4)
    opt_state = tx.init(params)

    @jax.jit
    def train_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(llama.loss_fn)(params, batch, cfg)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    if args.steps < 1:
        raise SystemExit("--steps must be >= 1")
    rng = np.random.default_rng(0)
    t0 = time.perf_counter()
    loss = None
    for step in range(args.steps):
        tokens = rng.integers(0, cfg.vocab_size, (args.batch_size, args.seq_len)).astype(np.int32)
        batch = {"input_ids": jax.device_put(tokens, data_sharding(mesh))}
        params, opt_state, loss = train_step(params, opt_state, batch)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step}: loss {float(jax.device_get(loss)):.4f}")
    dt = time.perf_counter() - t0
    tok = args.steps * args.batch_size * args.seq_len
    print(f"{tok / dt:.0f} tokens/s (incl. compile)")
    return float(jax.device_get(loss))


if __name__ == "__main__":
    main()
