"""Text-pair classification example — the repo's analog of the reference
``examples/nlp_example.py`` (BERT on GLUE/MRPC).

Same shape as the reference script: build dataloaders, construct
``Accelerator``, ``prepare(model, optimizer, dataloader, scheduler)``, train
with ``accelerator.backward``, evaluate with ``gather_for_metrics``.  The model
is a self-contained embedding classifier (no Hub download — this image has no
network egress) trained on a synthetic paraphrase-detection task, so the script
runs anywhere in seconds; swap in any fx-traceable torch model unchanged.

Run:  python examples/nlp_example.py [--mixed_precision bf16] [--cpu]
"""

import argparse

import numpy as np
import torch
from torch.optim.lr_scheduler import LambdaLR
from torch.utils.data import DataLoader

from accelerate_tpu import Accelerator
from accelerate_tpu.utils import set_seed

VOCAB = 512
SEQ = 32
EVAL_BATCH_SIZE = 32


class PairClassifier(torch.nn.Module):
    """Mean-pooled embedding encoder over both sentences + MLP head."""

    def __init__(self, vocab=VOCAB, dim=64):
        super().__init__()
        self.embed = torch.nn.Embedding(vocab, dim)
        self.head = torch.nn.Sequential(
            torch.nn.Linear(4 * dim, 128), torch.nn.GELU(), torch.nn.Linear(128, 2)
        )

    def forward(self, input_ids_a, input_ids_b):
        a = self.embed(input_ids_a).mean(dim=1)
        b = self.embed(input_ids_b).mean(dim=1)
        feats = torch.cat([a, b, torch.abs(a - b), a * b], dim=1)
        return self.head(feats)


def make_dataset(n: int, seed: int):
    """Synthetic paraphrase pairs: positives are shuffled copies (+ noise),
    negatives are independent draws."""
    rng = np.random.default_rng(seed)
    a = rng.integers(1, VOCAB, (n, SEQ))
    labels = rng.integers(0, 2, n)
    b = np.where(
        labels[:, None] == 1,
        rng.permuted(a, axis=1),
        rng.integers(1, VOCAB, (n, SEQ)),
    )
    return [
        {
            "input_ids_a": torch.tensor(a[i]),
            "input_ids_b": torch.tensor(b[i]),
            "labels": int(labels[i]),
        }
        for i in range(n)
    ]


def collate(samples):
    return {
        "input_ids_a": torch.stack([s["input_ids_a"] for s in samples]),
        "input_ids_b": torch.stack([s["input_ids_b"] for s in samples]),
        "labels": torch.tensor([s["labels"] for s in samples]),
    }


def get_dataloaders(accelerator: Accelerator, batch_size: int = 16):
    train = make_dataset(512, seed=0)
    val = make_dataset(128, seed=1)
    return (
        DataLoader(train, shuffle=True, collate_fn=collate, batch_size=batch_size),
        DataLoader(val, shuffle=False, collate_fn=collate, batch_size=EVAL_BATCH_SIZE),
    )


def training_function(config, args):
    accelerator = Accelerator(cpu=args.cpu, mixed_precision=args.mixed_precision)
    lr = config["lr"]
    num_epochs = int(config["num_epochs"])
    seed = int(config["seed"])
    batch_size = int(config["batch_size"])

    set_seed(seed)
    train_dataloader, eval_dataloader = get_dataloaders(accelerator, batch_size)
    model = PairClassifier()
    optimizer = torch.optim.AdamW(params=model.parameters(), lr=lr)
    total_steps = num_epochs * len(train_dataloader)
    lr_scheduler = LambdaLR(optimizer, lambda step: max(0.0, 1.0 - step / max(total_steps, 1)))

    model, optimizer, train_dataloader, eval_dataloader, lr_scheduler = accelerator.prepare(
        model, optimizer, train_dataloader, eval_dataloader, lr_scheduler
    )

    criterion = torch.nn.CrossEntropyLoss()
    final_accuracy = 0.0
    for epoch in range(num_epochs):
        model.train()
        for batch in train_dataloader:
            logits = model(batch["input_ids_a"], batch["input_ids_b"])
            loss = criterion(logits, batch["labels"])
            accelerator.backward(loss)
            optimizer.step()
            lr_scheduler.step()
            optimizer.zero_grad()

        model.eval()
        correct, total = [], []
        for batch in eval_dataloader:
            logits = model(batch["input_ids_a"], batch["input_ids_b"])
            preds = torch.argmax(logits, dim=-1)
            preds, refs = accelerator.gather_for_metrics((preds, batch["labels"]))
            correct.append(int((preds == refs).sum()))
            total.append(len(refs))
        final_accuracy = float(sum(correct)) / max(sum(total), 1)
        accelerator.print(f"epoch {epoch}: accuracy {final_accuracy:.3f}")
    return final_accuracy


def main():
    parser = argparse.ArgumentParser(description="Text-pair classification example")
    parser.add_argument(
        "--mixed_precision",
        type=str,
        default=None,
        choices=["no", "fp16", "bf16", "fp8"],
        help="Whether to use mixed precision (fp16 maps to bf16 on TPU).",
    )
    parser.add_argument("--cpu", action="store_true", help="Force the CPU backend.")
    parser.add_argument("--num_epochs", type=int, default=3)
    args = parser.parse_args()
    config = {"lr": 2e-3, "num_epochs": args.num_epochs, "seed": 42, "batch_size": 16}
    training_function(config, args)


if __name__ == "__main__":
    main()
