"""Pipeline-parallel T5 inference (reference
``examples/inference/pippy/t5.py``): pipeline the ENCODER stack over ``pp``
(the relative-position bias is shared across layers, so it closes over every
stage identically); the decoder runs dense against the pipelined encoder
output."""

import os

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

from accelerate_tpu.state import honor_cpu_platform_env

honor_cpu_platform_env()

import numpy as np

import jax
import jax.numpy as jnp

from accelerate_tpu import AcceleratorState, ParallelismConfig
from accelerate_tpu.models import t5
from accelerate_tpu.parallel import pipeline as pl
from accelerate_tpu.parallel.sharding import data_sharding, shard_params


def main():
    n = jax.device_count()
    if n < 2:
        raise SystemExit(
            "This example needs >=2 devices for a pp axis. On one machine run it "
            "on the virtual CPU mesh:  JAX_PLATFORMS=cpu "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 python " + __file__
        )
    pp = 4 if n % 4 == 0 else 2
    state = AcceleratorState(parallelism_config=ParallelismConfig(pp=pp, dp=n // pp))

    cfg = t5.T5Config.tiny(num_layers=4)
    params = shard_params(
        t5.init_params(cfg, jax.random.key(0)), state.mesh, t5.param_specs(cfg)
    )
    stage_layers = pl.stack_pipeline_stages(params["encoder"], pp)

    s = 32

    @jax.jit
    def encode_pipelined(input_ids):
        enc_bias = t5._rel_bias(
            params["enc_rel_bias"].astype(jnp.float32), s, s, cfg, bidirectional=True
        )

        def stage_fn(lp, h):
            def body(carry, one_layer):
                return t5._enc_layer(carry, one_layer, c=cfg, bias=enc_bias, mask=None, act_spec=None)

            h, _ = jax.lax.scan(body, h, lp)
            return h

        x = params["shared_embed"].astype(cfg.dtype)[input_ids]
        x = pl.pipeline_apply(stage_fn, stage_layers, x, num_micro_batches=2)
        return t5._rms_norm(x, params["enc_final_ln"], cfg.rms_eps)

    ids = jax.device_put(
        np.random.randint(0, cfg.vocab_size, (8, s)).astype(np.int32),
        data_sharding(state.mesh),
    )
    enc_out = encode_pipelined(ids)
    dense = t5.encode(params, ids, cfg)
    np.testing.assert_allclose(np.asarray(enc_out), np.asarray(dense), atol=5e-2, rtol=1e-2)
    print(f"pipelined t5 encoder over pp={pp}: {enc_out.shape} (matches dense)")


if __name__ == "__main__":
    main()
