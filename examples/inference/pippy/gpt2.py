"""Pipeline-parallel GPT-2 inference (reference
``examples/inference/pippy/gpt2.py``): the generic ``stage_fn`` path —
stack the block params into pp-sharded stages and scan each stage's layers
with causal masking inside the stage body."""

import os

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

from accelerate_tpu.state import honor_cpu_platform_env

honor_cpu_platform_env()

import numpy as np

import jax
import jax.numpy as jnp

from accelerate_tpu import AcceleratorState, ParallelismConfig
from accelerate_tpu.models import gpt2
from accelerate_tpu.parallel import pipeline as pl
from accelerate_tpu.parallel.sharding import data_sharding, shard_params


def main():
    n = jax.device_count()
    if n < 2:
        raise SystemExit(
            "This example needs >=2 devices for a pp axis. On one machine run it "
            "on the virtual CPU mesh:  JAX_PLATFORMS=cpu "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 python " + __file__
        )
    pp = 4 if n % 4 == 0 else 2
    state = AcceleratorState(parallelism_config=ParallelismConfig(pp=pp, dp=n // pp))

    cfg = gpt2.GPT2Config.tiny(num_layers=4)
    params = shard_params(
        gpt2.init_params(cfg, jax.random.key(0)), state.mesh, gpt2.param_specs(cfg)
    )
    stage_layers = pl.stack_pipeline_stages(params["layers"], pp)

    def stage_fn(lp, h):
        mb, s, _ = h.shape
        mask = jnp.broadcast_to(jnp.tril(jnp.ones((s, s), bool)), (mb, s, s))

        def body(carry, one_layer):
            return gpt2._layer(carry, one_layer, c=cfg, mask=mask, act_spec=None)

        h, _ = jax.lax.scan(body, h, lp)
        return h

    @jax.jit
    def forward(input_ids):
        s = input_ids.shape[1]
        x = params["wte"].astype(cfg.dtype)[input_ids] + params["wpe"].astype(cfg.dtype)[:s][None]
        x = pl.pipeline_apply(stage_fn, stage_layers, x, num_micro_batches=2)
        x = gpt2._layer_norm(x, params["final_ln_scale"], params["final_ln_bias"], cfg.layer_norm_eps)
        return (x @ params["wte"].astype(cfg.dtype).T).astype(jnp.float32)

    ids = jax.device_put(
        np.random.randint(0, cfg.vocab_size, (8, 32)).astype(np.int32),
        data_sharding(state.mesh),
    )
    logits = forward(ids)
    # Parity check vs the dense forward.
    dense = gpt2.apply(params, ids, cfg)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(dense), atol=5e-2, rtol=1e-2)
    print(f"pipelined gpt2 forward over pp={pp}: logits {logits.shape} (matches dense)")


if __name__ == "__main__":
    main()
