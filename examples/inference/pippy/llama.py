"""Pipeline-parallel llama inference (reference
``examples/inference/pippy/llama.py``): split the decoder stack over the
``pp`` mesh axis and run one jit-compiled GPipe schedule."""

import os

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

from accelerate_tpu.state import honor_cpu_platform_env

honor_cpu_platform_env()

import numpy as np

import jax

from accelerate_tpu import AcceleratorState, ParallelismConfig
from accelerate_tpu.inference import prepare_pippy
from accelerate_tpu.models import llama
from accelerate_tpu.parallel.sharding import data_sharding, shard_params


def main():
    n = jax.device_count()
    if n < 2:
        raise SystemExit(
            "This example needs >=2 devices for a pp axis. On one machine run it "
            "on the virtual CPU mesh:  JAX_PLATFORMS=cpu "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 python " + __file__
        )
    pp = 4 if n % 4 == 0 else 2
    state = AcceleratorState(parallelism_config=ParallelismConfig(pp=pp, dp=n // pp))

    cfg = llama.LlamaConfig.tiny(num_layers=4)
    params = shard_params(
        llama.init_params(cfg, jax.random.key(0)), state.mesh, llama.param_specs(cfg)
    )
    forward = prepare_pippy(params, cfg)

    ids = jax.device_put(
        np.random.randint(0, cfg.vocab_size, (8, 32)).astype(np.int32),
        data_sharding(state.mesh),
    )
    logits = forward(ids)
    jax.block_until_ready(logits)
    print(f"pipelined llama forward over pp={pp}: logits {logits.shape}")


if __name__ == "__main__":
    main()
