"""Pipeline-parallel BERT inference (reference
``examples/inference/pippy/bert.py``): generic ``stage_fn`` path with
bidirectional masking inside the stage body."""

import os

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

from accelerate_tpu.state import honor_cpu_platform_env

honor_cpu_platform_env()

import numpy as np

import jax
import jax.numpy as jnp

from accelerate_tpu import AcceleratorState, ParallelismConfig
from accelerate_tpu.models import bert
from accelerate_tpu.parallel import pipeline as pl
from accelerate_tpu.parallel.sharding import data_sharding, shard_params


def main():
    n = jax.device_count()
    if n < 2:
        raise SystemExit(
            "This example needs >=2 devices for a pp axis. On one machine run it "
            "on the virtual CPU mesh:  JAX_PLATFORMS=cpu "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 python " + __file__
        )
    pp = 4 if n % 4 == 0 else 2
    state = AcceleratorState(parallelism_config=ParallelismConfig(pp=pp, dp=n // pp))

    cfg = bert.BertConfig.tiny(num_layers=4)
    params = shard_params(
        bert.init_params(cfg, jax.random.key(0)), state.mesh, bert.param_specs(cfg)
    )
    stage_layers = pl.stack_pipeline_stages(params["layers"], pp)

    def stage_fn(lp, h):
        mb, s, _ = h.shape
        mask = jnp.ones((mb, s, s), bool)

        def body(carry, one_layer):
            return bert._layer(carry, one_layer, c=cfg, mask=mask, act_spec=None)

        h, _ = jax.lax.scan(body, h, lp)
        return h

    @jax.jit
    def encode(input_ids):
        s = input_ids.shape[1]
        e = params["embeddings"]
        x = (
            e["word"].astype(cfg.dtype)[input_ids]
            + e["position"].astype(cfg.dtype)[:s][None]
            + e["token_type"].astype(cfg.dtype)[jnp.zeros_like(input_ids)]
        )
        x = bert._layer_norm(x, e["ln_scale"], e["ln_bias"], cfg.layer_norm_eps)
        x = pl.pipeline_apply(stage_fn, stage_layers, x, num_micro_batches=2)
        pooled = jnp.tanh(
            x[:, 0].astype(jnp.float32) @ params["pooler"]["w"].astype(jnp.float32)
            + params["pooler"]["b"]
        )
        return x, pooled

    ids = jax.device_put(
        np.random.randint(0, cfg.vocab_size, (8, 32)).astype(np.int32),
        data_sharding(state.mesh),
    )
    seq_out, pooled = encode(ids)
    dense_seq, dense_pooled = bert.apply(params, ids, cfg)
    np.testing.assert_allclose(np.asarray(pooled), np.asarray(dense_pooled), atol=5e-2, rtol=1e-2)
    print(f"pipelined bert encoder over pp={pp}: pooled {pooled.shape} (matches dense)")


if __name__ == "__main__":
    main()
