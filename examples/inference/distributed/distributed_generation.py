"""Distributed batch generation (reference
``examples/inference/distributed/phi2.py`` pattern): shard a prompt list
across processes with ``split_between_processes``, generate on each slice
with the one-jit KV-cache decode loop, gather the results.

On a single host this degenerates to one slice; under a multi-host launch
(``accelerate-tpu launch --num_machines N ...``) each host generates its
share and ``gather_object`` reassembles the full list on every rank.
"""

import os

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

from accelerate_tpu.state import honor_cpu_platform_env

honor_cpu_platform_env()

import numpy as np

import jax

from accelerate_tpu import PartialState
from accelerate_tpu.models import llama
from accelerate_tpu.utils import gather_object


def main():
    state = PartialState()
    cfg = llama.LlamaConfig.tiny(num_layers=2)
    params = llama.init_params(cfg, jax.random.key(0))

    # 8 synthetic "prompts" (token id arrays — a tokenizer would produce these).
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size, size=8).tolist() for _ in range(8)]

    completions = []
    with state.split_between_processes(prompts) as my_prompts:
        if my_prompts:
            ids = np.asarray(my_prompts, np.int32)
            out = llama.generate(params, ids, cfg, max_new_tokens=8)
            completions = np.asarray(out).tolist()

    all_completions = gather_object(completions)
    state.print(f"{len(all_completions)} completions from {state.num_processes} process(es); "
                f"first: {all_completions[0]}")


if __name__ == "__main__":
    main()
