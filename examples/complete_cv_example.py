"""Complete CV training example — the repo's analog of the reference
``examples/complete_cv_example.py`` (329 LoC): the canonical ``cv_example``
plus tracking, step/epoch checkpointing, full resume (mid-epoch via
``skip_first_batches``), and gradient accumulation, all CLI-controlled.

Run:
  python examples/complete_cv_example.py --checkpointing_steps epoch \
      --with_tracking --project_dir ./complete_cv
"""

import argparse
import os

import torch
from torch.optim.lr_scheduler import LambdaLR
from torch.utils.data import DataLoader

from accelerate_tpu import Accelerator, skip_first_batches
from accelerate_tpu.utils import ProjectConfiguration, set_seed

import importlib.util as _ilu

_spec = _ilu.spec_from_file_location(
    "cv_example", os.path.join(os.path.dirname(os.path.abspath(__file__)), "cv_example.py")
)
cv = _ilu.module_from_spec(_spec)
_spec.loader.exec_module(cv)


def training_function(config, args):
    project_config = ProjectConfiguration(
        project_dir=args.project_dir, automatic_checkpoint_naming=True, total_limit=3
    )
    accelerator = Accelerator(
        cpu=args.cpu,
        mixed_precision=args.mixed_precision,
        gradient_accumulation_steps=args.gradient_accumulation_steps,
        log_with="generic" if args.with_tracking else None,
        project_config=project_config,
    )
    if args.with_tracking:
        accelerator.init_trackers("complete_cv_example", config)

    set_seed(config["seed"])
    train_dl = DataLoader(
        cv.make_dataset(512, 0), shuffle=True, collate_fn=cv.collate, batch_size=config["batch_size"]
    )
    eval_dl = DataLoader(cv.make_dataset(128, 1), collate_fn=cv.collate, batch_size=32)
    model = cv.SmallCNN()
    optimizer = torch.optim.AdamW(model.parameters(), lr=config["lr"])
    total = config["num_epochs"] * len(train_dl)
    scheduler = LambdaLR(optimizer, lambda step: max(0.0, 1.0 - step / max(total, 1)))
    model, optimizer, train_dl, eval_dl, scheduler = accelerator.prepare(
        model, optimizer, train_dl, eval_dl, scheduler
    )

    starting_epoch = 0
    resume_step = None
    if args.resume_from_checkpoint:
        accelerator.load_state(args.resume_from_checkpoint)
        name = os.path.basename(os.path.normpath(args.resume_from_checkpoint))
        ckpt_idx = int(name.split("_")[-1])
        if args.checkpointing_steps == "epoch" or args.checkpointing_steps is None:
            starting_epoch = ckpt_idx + 1
        else:
            step_every = int(args.checkpointing_steps)
            consumed = (ckpt_idx + 1) * step_every
            starting_epoch = consumed // len(train_dl)
            resume_step = consumed % len(train_dl)

    criterion = torch.nn.CrossEntropyLoss()
    overall_step = 0
    accuracy = 0.0
    for epoch in range(starting_epoch, config["num_epochs"]):
        model.train()
        total_loss = 0.0
        active_dl = train_dl
        if resume_step is not None:
            active_dl = skip_first_batches(train_dl, resume_step)
            overall_step += resume_step
            resume_step = None
        for batch in active_dl:
            with accelerator.accumulate(model):
                loss = criterion(model(batch["pixels"]), batch["labels"])
                total_loss += float(loss.detach())
                accelerator.backward(loss)
                optimizer.step()
                scheduler.step()
                optimizer.zero_grad()
            overall_step += 1
            if isinstance(args.checkpointing_steps, str) and args.checkpointing_steps.isdigit():
                if overall_step % int(args.checkpointing_steps) == 0:
                    accelerator.save_state()
        if args.checkpointing_steps == "epoch":
            accelerator.save_state()

        model.eval()
        hits, n = 0, 0
        for batch in eval_dl:
            with torch.no_grad():
                logits = model(batch["pixels"])
            preds = torch.argmax(logits, dim=-1)
            preds, refs = accelerator.gather_for_metrics((preds, batch["labels"]))
            hits += int((preds == refs).sum())
            n += len(refs)
        accuracy = hits / max(n, 1)
        accelerator.print(f"epoch {epoch}: accuracy {accuracy:.3f}")
        if args.with_tracking:
            accelerator.log(
                {
                    "accuracy": accuracy,
                    "train_loss": total_loss / max(len(train_dl), 1),
                    "epoch": epoch,
                },
                step=epoch,
            )

    if args.with_tracking:
        accelerator.end_training()
    return accuracy


def main():
    parser = argparse.ArgumentParser(description="Complete CV training example")
    parser.add_argument("--mixed_precision", type=str, default=None, choices=["no", "fp16", "bf16", "fp8"])
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--checkpointing_steps", type=str, default=None)
    parser.add_argument("--resume_from_checkpoint", type=str, default=None)
    parser.add_argument("--with_tracking", action="store_true")
    parser.add_argument("--project_dir", type=str, default="./complete_cv")
    parser.add_argument("--gradient_accumulation_steps", type=int, default=1)
    parser.add_argument("--num_epochs", type=int, default=2)
    args = parser.parse_args()
    config = {"lr": 3e-3, "num_epochs": args.num_epochs, "seed": 42, "batch_size": 64}
    training_function(config, args)


if __name__ == "__main__":
    main()
