"""Remote multi-host launcher (analog of the reference
``examples/multigpu_remote_launcher.py``, which fans a training function out
to remote GPUs via runhouse): fan a command out to every VM of a TPU pod via
the ``tpu-config`` gcloud ssh machinery, wiring the coordinator env on each
worker.

Run:  python examples/multitpu_remote_launcher.py --tpu_name my-pod \
          --tpu_zone us-central2-b -- python train.py --bf16
"""

import argparse
import shlex


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--tpu_name", required=True)
    parser.add_argument("--tpu_zone", required=True)
    parser.add_argument("--num_machines", type=int, default=None,
                        help="hosts in the pod (default: let gcloud target all workers)")
    parser.add_argument("--main_process_port", type=int, default=8476)
    parser.add_argument("cmd", nargs=argparse.REMAINDER,
                        help="command to run on every worker (prefix with --)")
    args = parser.parse_args()
    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    if not cmd:
        parser.error("pass the training command after --")

    # Worker 0's internal address doubles as the coordinator; each worker
    # learns its rank from the gcloud worker index env.
    inner = (
        "ACCELERATE_COORDINATOR_ADDRESS=${TPU_WORKER_0_IP}:%d "
        "ACCELERATE_PROCESS_ID=${TPU_WORKER_ID} "
        % args.main_process_port
    ) + shlex.join(cmd)

    from accelerate_tpu.commands.tpu import tpu_command

    ns = argparse.Namespace(
        config_file=None,
        tpu_name=args.tpu_name,
        tpu_zone=args.tpu_zone,
        command=[inner],
        command_file=None,
        install_accelerate=False,
        accelerate_version="latest",
        debug=True,  # print the gcloud fan-out; drop for a real pod
    )
    tpu_command(ns)


if __name__ == "__main__":
    main()
