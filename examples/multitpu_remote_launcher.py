"""Remote multi-host launcher (analog of the reference
``examples/multigpu_remote_launcher.py``, which fans a training function out
to remote GPUs via runhouse): fan a command out to every VM of a TPU pod via
the ``tpu-config`` gcloud ssh machinery.

No coordinator env is needed on a real TPU pod: with the
``ACCELERATE_TPU_POD=1`` marker, ``PartialState`` calls
``jax.distributed.initialize()`` bare and JAX discovers the coordinator and
each host's process index from TPU-VM metadata.

Run (prints the gcloud command; add --run to execute it):
    python examples/multitpu_remote_launcher.py --tpu_name my-pod \
        --tpu_zone us-central2-b -- accelerate-tpu launch train.py
"""

import argparse
import shlex


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--tpu_name", required=True)
    parser.add_argument("--tpu_zone", required=True)
    parser.add_argument("--run", action="store_true",
                        help="Execute the gcloud fan-out (default: print it)")
    parser.add_argument("cmd", nargs=argparse.REMAINDER,
                        help="command to run on every worker (prefix with --)")
    args = parser.parse_args()
    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    if not cmd:
        parser.error("pass the training command after --")

    from accelerate_tpu.commands.tpu import tpu_command

    ns = argparse.Namespace(
        config_file=None,
        tpu_name=args.tpu_name,
        tpu_zone=args.tpu_zone,
        command=["ACCELERATE_TPU_POD=1 " + shlex.join(cmd)],
        command_file=None,
        install_accelerate=False,
        accelerate_version="latest",
        debug=not args.run,
    )
    tpu_command(ns)


if __name__ == "__main__":
    main()
