#!/bin/bash
#SBATCH --job-name=atpu-pod
#SBATCH --nodes=4
#SBATCH --ntasks-per-node=1
#SBATCH --output=%x_%j.out

# Multi-host slice: one launcher task per host.  Host 0 of the allocation is
# the JAX distributed coordinator (reference submit_multinode.sh wires
# MASTER_ADDR the same way for torchrun).
export COORD_ADDR=$(scontrol show hostnames "$SLURM_JOB_NODELIST" | head -n 1)
export COORD_PORT=8476

srun accelerate-tpu launch \
    --num_machines "$SLURM_NNODES" \
    --machine_rank "$SLURM_NODEID" \
    --main_process_ip "$COORD_ADDR" \
    --main_process_port "$COORD_PORT" \
    --mixed_precision bf16 \
    examples/complete_nlp_example.py --checkpointing_steps epoch
