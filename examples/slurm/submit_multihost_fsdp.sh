#!/bin/bash
#SBATCH --job-name=atpu-pod-fsdp
#SBATCH --nodes=4
#SBATCH --ntasks-per-node=1
#SBATCH --output=%x_%j.out

# Multi-host FSDP (ZeRO-3-equivalent GSPMD sharding over every chip in the
# slice); pairs with examples/slurm/fsdp_config.yaml.
export COORD_ADDR=$(scontrol show hostnames "$SLURM_JOB_NODELIST" | head -n 1)

srun accelerate-tpu launch \
    --config_file examples/slurm/fsdp_config.yaml \
    --num_machines "$SLURM_NNODES" \
    --machine_rank "$SLURM_NODEID" \
    --main_process_ip "$COORD_ADDR" \
    --main_process_port 8476 \
    examples/complete_nlp_example.py --checkpointing_steps epoch
