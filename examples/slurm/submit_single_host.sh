#!/bin/bash
#SBATCH --job-name=atpu-single
#SBATCH --nodes=1
#SBATCH --ntasks-per-node=1
#SBATCH --output=%x_%j.out

# Single TPU host: all local chips, data-parallel by default.
srun accelerate-tpu launch \
    --mixed_precision bf16 \
    examples/complete_nlp_example.py --checkpointing_steps epoch
