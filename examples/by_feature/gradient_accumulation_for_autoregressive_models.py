"""Feature: gradient accumulation for autoregressive LMs with correct
cross-micro-batch loss normalization (reference
``examples/by_feature/gradient_accumulation_for_autoregressive_models.py``).

Plain ``accumulate()`` scales each micro-batch loss by 1/steps — correct when
every micro-batch holds the same number of loss tokens, WRONG for causal LM
batches of varying length.  The fix (same as the reference): normalize by the
number of non-padding tokens summed over the whole accumulation window, not
per micro-batch.

Run: python examples/by_feature/gradient_accumulation_for_autoregressive_models.py
"""

import argparse

import numpy as np
import torch
from torch.utils.data import DataLoader

from accelerate_tpu import Accelerator
from accelerate_tpu.utils import set_seed

VOCAB = 256
PAD = 0


class TinyCausalLM(torch.nn.Module):
    def __init__(self, vocab=VOCAB, dim=64):
        super().__init__()
        self.embed = torch.nn.Embedding(vocab, dim)
        self.proj = torch.nn.Linear(dim, dim)
        self.head = torch.nn.Linear(dim, vocab)

    def forward(self, input_ids):
        h = self.embed(input_ids)
        # Causal mixing: cumulative mean over positions (no future leakage).
        h = torch.cumsum(self.proj(h), dim=1) / torch.arange(
            1, h.shape[1] + 1, device=h.device
        ).view(1, -1, 1)
        return self.head(h)


def make_dataset(n: int, seed: int):
    """Variable-length repeated-pattern sequences, padded to 32."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        length = int(rng.integers(8, 33))
        pattern = rng.integers(1, VOCAB, 4)
        ids = np.tile(pattern, 9)[:length]
        padded = np.full(32, PAD)
        padded[:length] = ids
        out.append(torch.tensor(padded))
    return out


def collate(samples):
    return {"input_ids": torch.stack(samples)}


def training_function(config, args):
    accelerator = Accelerator(
        cpu=args.cpu,
        mixed_precision=args.mixed_precision,
        gradient_accumulation_steps=args.gradient_accumulation_steps,
    )
    set_seed(int(config["seed"]))
    data = make_dataset(256, seed=0)
    train_dataloader = DataLoader(data, shuffle=True, collate_fn=collate, batch_size=8)
    model = TinyCausalLM()
    optimizer = torch.optim.AdamW(model.parameters(), lr=config["lr"])
    model, optimizer, train_dataloader = accelerator.prepare(model, optimizer, train_dataloader)

    n_accum = args.gradient_accumulation_steps
    losses = []
    batches = list(train_dataloader)
    for epoch in range(int(config["num_epochs"])):
        model.train()
        for window_start in range(0, len(batches) - n_accum + 1, n_accum):
            window = batches[window_start : window_start + n_accum]
            # Token count over the WHOLE window: the correct normalizer.
            num_tokens = sum(int((b["input_ids"][:, 1:] != PAD).sum()) for b in window)
            for batch in window:
                with accelerator.accumulate(model):
                    ids = batch["input_ids"]
                    logits = model(ids[:, :-1])
                    targets = ids[:, 1:]
                    token_loss = torch.nn.functional.cross_entropy(
                        logits.reshape(-1, VOCAB), targets.reshape(-1), reduction="none"
                    )
                    mask = (targets != PAD).reshape(-1).float()
                    # Sum (not mean) over tokens, divided by the window total;
                    # accumulate() multiplies by 1/n_accum, so pre-multiply by
                    # n_accum to cancel it (reference's trick).
                    loss = (token_loss * mask).sum() * n_accum / max(num_tokens, 1)
                    accelerator.backward(loss)
                    optimizer.step()
                    optimizer.zero_grad()
                    losses.append(float(loss.detach()) / n_accum)
        accelerator.print(f"epoch {epoch}: loss {np.mean(losses[-10:]):.4f}")
    return losses[0], float(np.mean(losses[-10:]))


def main():
    parser = argparse.ArgumentParser(description="Autoregressive grad-accum example")
    parser.add_argument("--mixed_precision", type=str, default=None,
                        choices=["no", "fp16", "bf16", "fp8"])
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--gradient_accumulation_steps", type=int, default=2)
    parser.add_argument("--num_epochs", type=int, default=2)
    args = parser.parse_args()
    config = {"lr": 2e-3, "num_epochs": args.num_epochs, "seed": 42}
    training_function(config, args)


if __name__ == "__main__":
    main()
