"""Feature: combine ``find_executable_batch_size`` with automatic gradient
accumulation (reference ``examples/by_feature/automatic_gradient_accumulation.py``).

The script targets an OBSERVED batch size (``--target_batch_size``): if the
device can't fit it, the OOM-retry halves the per-step batch and doubles
``gradient_accumulation_steps`` so the effective batch stays constant.

Run: python examples/by_feature/automatic_gradient_accumulation.py --target_batch_size 64
"""

import argparse

import torch
from torch.optim.lr_scheduler import LambdaLR

from accelerate_tpu import Accelerator, find_executable_batch_size
from accelerate_tpu.utils import set_seed

from _base import load_nlp_example

nlp = load_nlp_example()


def training_function(config, args):
    set_seed(int(config["seed"]))
    observed = []

    @find_executable_batch_size(starting_batch_size=args.target_batch_size)
    def inner_training_loop(batch_size):
        # Keep the effective batch at target by accumulating the difference.
        accumulation_steps = max(1, args.target_batch_size // batch_size)
        observed.append((batch_size, accumulation_steps))
        accelerator = Accelerator(
            cpu=args.cpu,
            mixed_precision=args.mixed_precision,
            gradient_accumulation_steps=accumulation_steps,
        )
        train_dataloader, eval_dataloader = nlp.get_dataloaders(accelerator, batch_size)
        model = nlp.PairClassifier()
        optimizer = torch.optim.AdamW(model.parameters(), lr=config["lr"])
        total_steps = int(config["num_epochs"]) * len(train_dataloader)
        lr_scheduler = LambdaLR(optimizer, lambda step: max(0.0, 1.0 - step / max(total_steps, 1)))
        model, optimizer, train_dataloader, eval_dataloader, lr_scheduler = accelerator.prepare(
            model, optimizer, train_dataloader, eval_dataloader, lr_scheduler
        )
        criterion = torch.nn.CrossEntropyLoss()
        final_accuracy = 0.0
        for epoch in range(int(config["num_epochs"])):
            model.train()
            for batch in train_dataloader:
                with accelerator.accumulate(model):
                    logits = model(batch["input_ids_a"], batch["input_ids_b"])
                    loss = criterion(logits, batch["labels"])
                    accelerator.backward(loss)
                    optimizer.step()
                    lr_scheduler.step()
                    optimizer.zero_grad()
            model.eval()
            correct, total = 0, 0
            for batch in eval_dataloader:
                with torch.no_grad():
                    logits = model(batch["input_ids_a"], batch["input_ids_b"])
                preds = torch.argmax(logits, dim=-1)
                preds, refs = accelerator.gather_for_metrics((preds, batch["labels"]))
                correct += int((preds == refs).sum())
                total += len(refs)
            final_accuracy = correct / max(total, 1)
            accelerator.print(
                f"epoch {epoch}: accuracy {final_accuracy:.3f} "
                f"(batch {batch_size} x accum {accumulation_steps})"
            )
        accelerator.free_memory()
        return final_accuracy

    acc = inner_training_loop()
    print(f"(batch_size, accumulation_steps) tried: {observed}")
    return acc


def main():
    parser = argparse.ArgumentParser(description="Automatic gradient-accumulation example")
    parser.add_argument("--mixed_precision", type=str, default=None,
                        choices=["no", "fp16", "bf16", "fp8"])
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--target_batch_size", type=int, default=64)
    parser.add_argument("--num_epochs", type=int, default=2)
    args = parser.parse_args()
    config = {"lr": 2e-3, "num_epochs": args.num_epochs, "seed": 42, "batch_size": 16}
    training_function(config, args)


if __name__ == "__main__":
    main()
