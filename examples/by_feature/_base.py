"""Shared loader for by_feature examples: imports the canonical nlp_example
components so each feature script shows ONLY its feature's delta (the
reference keeps its by_feature scripts in sync with the canonical example via
AST diff, tests/test_examples.py; importing makes the sync structural)."""

import importlib.util
import os
import sys

_EXAMPLES_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_nlp_example():
    path = os.path.join(_EXAMPLES_DIR, "nlp_example.py")
    spec = importlib.util.spec_from_file_location("nlp_example", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("nlp_example", mod)
    spec.loader.exec_module(mod)
    return mod
