"""Feature: correct distributed metrics with ``gather_for_metrics`` (reference
``examples/by_feature/multi_process_metrics.py``).

A plain ``gather`` over an even-batches dataloader double-counts the samples
that were duplicated to pad the last batch; ``gather_for_metrics`` strips that
padding so the metric equals the single-process value exactly.

Run: python examples/by_feature/multi_process_metrics.py
"""

import argparse

import torch
from torch.optim.lr_scheduler import LambdaLR

from accelerate_tpu import Accelerator
from accelerate_tpu.utils import set_seed

from _base import load_nlp_example

nlp = load_nlp_example()


def training_function(config, args):
    accelerator = Accelerator(cpu=args.cpu, mixed_precision=args.mixed_precision)
    set_seed(int(config["seed"]))
    train_dataloader, eval_dataloader = nlp.get_dataloaders(accelerator, int(config["batch_size"]))
    model = nlp.PairClassifier()
    optimizer = torch.optim.AdamW(model.parameters(), lr=config["lr"])
    total_steps = int(config["num_epochs"]) * len(train_dataloader)
    lr_scheduler = LambdaLR(optimizer, lambda step: max(0.0, 1.0 - step / max(total_steps, 1)))

    model, optimizer, train_dataloader, eval_dataloader, lr_scheduler = accelerator.prepare(
        model, optimizer, train_dataloader, eval_dataloader, lr_scheduler
    )

    criterion = torch.nn.CrossEntropyLoss()
    final_accuracy = 0.0
    for epoch in range(int(config["num_epochs"])):
        model.train()
        for batch in train_dataloader:
            logits = model(batch["input_ids_a"], batch["input_ids_b"])
            loss = criterion(logits, batch["labels"])
            accelerator.backward(loss)
            optimizer.step()
            lr_scheduler.step()
            optimizer.zero_grad()

        model.eval()
        all_preds, all_refs = [], []
        for batch in eval_dataloader:
            with torch.no_grad():
                logits = model(batch["input_ids_a"], batch["input_ids_b"])
            preds = torch.argmax(logits, dim=-1)
            # Gathers across processes AND drops the even-batches duplicates
            # of the final batch; len(sum of gathered) == len(dataset).
            preds, refs = accelerator.gather_for_metrics((preds, batch["labels"]))
            all_preds.append(preds)
            all_refs.append(refs)
        preds = torch.cat(all_preds)
        refs = torch.cat(all_refs)
        final_accuracy = float((preds == refs).float().mean())
        accelerator.print(
            f"epoch {epoch}: accuracy {final_accuracy:.3f} over exactly {len(refs)} samples"
        )
    return final_accuracy


def main():
    parser = argparse.ArgumentParser(description="Distributed-metrics example")
    parser.add_argument("--mixed_precision", type=str, default=None,
                        choices=["no", "fp16", "bf16", "fp8"])
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--num_epochs", type=int, default=3)
    args = parser.parse_args()
    config = {"lr": 2e-3, "num_epochs": args.num_epochs, "seed": 42, "batch_size": 16}
    training_function(config, args)


if __name__ == "__main__":
    main()
