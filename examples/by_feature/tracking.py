"""Feature: experiment tracking with ``init_trackers``/``log``/``end_training``
(reference ``examples/by_feature/tracking.py``).

``log_with="all"`` activates every tracker whose backend is importable
(TensorBoard, WandB, CometML, Aim, MLflow, ClearML, DVCLive) plus the
dependency-free JSONL tracker; in this image that typically means
tensorboard + jsonl.

Run: python examples/by_feature/tracking.py --with_tracking --project_dir ./track_demo
"""

import argparse

import torch
from torch.optim.lr_scheduler import LambdaLR

from accelerate_tpu import Accelerator
from accelerate_tpu.utils import set_seed

from _base import load_nlp_example

nlp = load_nlp_example()


def training_function(config, args):
    accelerator = Accelerator(
        cpu=args.cpu,
        mixed_precision=args.mixed_precision,
        log_with="all" if args.with_tracking else None,
        project_dir=args.project_dir,
    )
    set_seed(int(config["seed"]))
    train_dataloader, eval_dataloader = nlp.get_dataloaders(accelerator, int(config["batch_size"]))
    model = nlp.PairClassifier()
    optimizer = torch.optim.AdamW(model.parameters(), lr=config["lr"])
    total_steps = int(config["num_epochs"]) * len(train_dataloader)
    lr_scheduler = LambdaLR(optimizer, lambda step: max(0.0, 1.0 - step / max(total_steps, 1)))

    model, optimizer, train_dataloader, eval_dataloader, lr_scheduler = accelerator.prepare(
        model, optimizer, train_dataloader, eval_dataloader, lr_scheduler
    )

    if args.with_tracking:
        accelerator.init_trackers("nlp_example_tracking", config=config)

    criterion = torch.nn.CrossEntropyLoss()
    overall_step = 0
    final_accuracy = 0.0
    for epoch in range(int(config["num_epochs"])):
        model.train()
        total_loss = 0.0
        for batch in train_dataloader:
            logits = model(batch["input_ids_a"], batch["input_ids_b"])
            loss = criterion(logits, batch["labels"])
            total_loss += float(loss.detach())
            accelerator.backward(loss)
            optimizer.step()
            lr_scheduler.step()
            optimizer.zero_grad()
            overall_step += 1

        model.eval()
        correct, total = 0, 0
        for batch in eval_dataloader:
            with torch.no_grad():
                logits = model(batch["input_ids_a"], batch["input_ids_b"])
            preds = torch.argmax(logits, dim=-1)
            preds, refs = accelerator.gather_for_metrics((preds, batch["labels"]))
            correct += int((preds == refs).sum())
            total += len(refs)
        final_accuracy = correct / max(total, 1)
        accelerator.print(f"epoch {epoch}: accuracy {final_accuracy:.3f}")
        if args.with_tracking:
            accelerator.log(
                {
                    "accuracy": final_accuracy,
                    "train_loss": total_loss / len(train_dataloader),
                    "epoch": epoch,
                },
                step=overall_step,
            )
    if args.with_tracking:
        accelerator.end_training()
    return final_accuracy


def main():
    parser = argparse.ArgumentParser(description="Tracking example")
    parser.add_argument("--mixed_precision", type=str, default=None,
                        choices=["no", "fp16", "bf16", "fp8"])
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--with_tracking", action="store_true")
    parser.add_argument("--project_dir", type=str, default="./track_demo")
    parser.add_argument("--num_epochs", type=int, default=3)
    args = parser.parse_args()
    config = {"lr": 2e-3, "num_epochs": args.num_epochs, "seed": 42, "batch_size": 16}
    training_function(config, args)


if __name__ == "__main__":
    main()
