"""Feature: DeepSpeed config-file support (reference
``examples/by_feature/deepspeed_with_config_support.py``).

A ``ds_config.json`` is accepted as a *dialect*: ZeRO stage → GSPMD sharding
strategy on the ``fsdp`` mesh axis (stage 3 = FULL_SHARD, 2 = SHARD_GRAD_OP,
0/1 = replicated), ``gradient_accumulation_steps``/``bf16``/clipping picked up
from the config, and ``optimizer``/``scheduler`` sections materialized through
``DummyOptim``/``DummyScheduler`` exactly like the reference.

Run: python examples/by_feature/deepspeed_with_config_support.py \
        [--config_file my_ds_config.json]
"""

import argparse
import json
import os
import tempfile

import torch

from accelerate_tpu import Accelerator
from accelerate_tpu.utils import set_seed
from accelerate_tpu.utils.deepspeed import DeepSpeedPlugin
from accelerate_tpu.utils.deepspeed import DummyOptim, DummyScheduler

from _base import load_nlp_example

nlp = load_nlp_example()

DEFAULT_DS_CONFIG = {
    "train_micro_batch_size_per_gpu": 16,
    "gradient_accumulation_steps": 1,
    "zero_optimization": {"stage": 2},
    "bf16": {"enabled": True},
    "gradient_clipping": 1.0,
    "optimizer": {"type": "AdamW", "params": {"lr": 2e-3, "weight_decay": 0.0}},
    "scheduler": {
        "type": "WarmupDecayLR",
        "params": {"warmup_num_steps": 4, "total_num_steps": 100, "warmup_min_lr": 0.0},
    },
}


def training_function(config, args):
    if args.config_file:
        ds_config_path = args.config_file
    else:
        fd, ds_config_path = tempfile.mkstemp(suffix=".json")
        with os.fdopen(fd, "w") as f:
            json.dump(DEFAULT_DS_CONFIG, f)

    plugin = DeepSpeedPlugin(hf_ds_config=ds_config_path)
    accelerator = Accelerator(cpu=args.cpu, deepspeed_plugin=plugin)
    set_seed(int(config["seed"]))
    train_dataloader, eval_dataloader = nlp.get_dataloaders(accelerator, int(config["batch_size"]))
    model = nlp.PairClassifier()
    # Optimizer/scheduler come from the DS config sections: pass Dummy objects,
    # prepare() materializes real ones with the config's hyperparameters.
    optimizer = DummyOptim(model.parameters())
    lr_scheduler = DummyScheduler(optimizer)

    model, optimizer, train_dataloader, eval_dataloader, lr_scheduler = accelerator.prepare(
        model, optimizer, train_dataloader, eval_dataloader, lr_scheduler
    )

    criterion = torch.nn.CrossEntropyLoss()
    final_accuracy = 0.0
    for epoch in range(int(config["num_epochs"])):
        model.train()
        for batch in train_dataloader:
            with accelerator.accumulate(model):
                logits = model(batch["input_ids_a"], batch["input_ids_b"])
                loss = criterion(logits, batch["labels"])
                accelerator.backward(loss)
                optimizer.step()
                lr_scheduler.step()
                optimizer.zero_grad()

        model.eval()
        correct, total = 0, 0
        for batch in eval_dataloader:
            with torch.no_grad():
                logits = model(batch["input_ids_a"], batch["input_ids_b"])
            preds = torch.argmax(logits, dim=-1)
            preds, refs = accelerator.gather_for_metrics((preds, batch["labels"]))
            correct += int((preds == refs).sum())
            total += len(refs)
        final_accuracy = correct / max(total, 1)
        accelerator.print(f"epoch {epoch}: accuracy {final_accuracy:.3f}")
    return final_accuracy


def main():
    parser = argparse.ArgumentParser(description="DeepSpeed-config-dialect example")
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--config_file", type=str, default=None,
                        help="Path to a DeepSpeed JSON config (default: built-in zero-2).")
    parser.add_argument("--num_epochs", type=int, default=3)
    args = parser.parse_args()
    config = {"num_epochs": args.num_epochs, "seed": 42, "batch_size": 16}
    training_function(config, args)


if __name__ == "__main__":
    main()
