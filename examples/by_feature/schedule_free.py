"""Feature: Schedule-Free optimization (reference
``examples/by_feature/schedule_free.py``, which uses the ``schedulefree``
package).

TPU-native version: ``optax.contrib.schedule_free_adamw`` wraps the update in
the same interpolation/averaging scheme — no LR scheduler needed — applied to
the JAX-native llama pretraining loop.

Run: python examples/by_feature/schedule_free.py --steps 30
"""

import argparse

import numpy as np

import jax
import optax

from accelerate_tpu import Accelerator
from accelerate_tpu.models import llama
from accelerate_tpu.parallel.sharding import data_sharding, make_param_specs, shard_params
from accelerate_tpu.utils import set_seed


def training_function(config, args):
    accelerator = Accelerator()
    mesh = accelerator.mesh
    set_seed(int(config["seed"]))

    cfg = llama.LlamaConfig.tiny(
        num_layers=int(config["layers"]), hidden_size=int(config["hidden"]), vocab_size=4096
    )
    params = llama.init_params(cfg, jax.random.key(0))
    specs = make_param_specs(
        params, mesh, accelerator.state.fsdp_plugin, rules=llama.PARTITION_RULES
    )
    params = shard_params(params, mesh, specs)

    # The schedule-free transform replaces the LR scheduler entirely: constant
    # peak LR + iterate averaging (y/z interpolation) inside the optimizer.
    tx = optax.contrib.schedule_free_adamw(
        learning_rate=config["lr"], warmup_steps=args.warmup_steps, b1=0.9
    )
    opt_state = tx.init(params)

    @jax.jit
    def train_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(llama.loss_fn)(params, batch, cfg)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # Small fixed corpus (cycled): loss visibly drops as the model fits it.
    rng = np.random.default_rng(0)
    corpus = [rng.integers(0, cfg.vocab_size, (8, 64)).astype(np.int32) for _ in range(4)]
    first = last = None
    for step in range(args.steps):
        tokens = corpus[step % len(corpus)]
        batch = {"input_ids": jax.device_put(tokens, data_sharding(mesh))}
        params, opt_state, loss = train_step(params, opt_state, batch)
        last = float(jax.device_get(loss))
        if first is None:
            first = last
        if step % 10 == 0 or step == args.steps - 1:
            accelerator.print(f"step {step}: loss {last:.4f}")

    # Evaluation uses the averaged (x) iterate, not the training (y) iterate.
    eval_params = optax.contrib.schedule_free_eval_params(opt_state, params)
    batch = {"input_ids": jax.device_put(corpus[0], data_sharding(mesh))}
    eval_loss = float(jax.device_get(llama.loss_fn(eval_params, batch, cfg)))
    accelerator.print(f"eval loss on averaged iterate: {eval_loss:.4f}")
    return first, last


def main():
    parser = argparse.ArgumentParser(description="Schedule-free optimizer example")
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--warmup_steps", type=int, default=5)
    args = parser.parse_args()
    config = {"lr": 3e-3, "seed": 42, "layers": 2, "hidden": 64}
    training_function(config, args)


if __name__ == "__main__":
    main()
