"""Feature: compressed gradient communication via the DDP comm-hook kwargs
(reference ``examples/by_feature/ddp_comm_hook.py``).

The reference registers fp16/bf16 compression hooks on
``torch.nn.parallel.DistributedDataParallel``; here
``DistributedDataParallelKwargs(comm_hook="bf16")`` makes the bridge hold the
accumulated/synced gradient pytree in bf16 — half the gradient storage and
half the bytes wherever gradients cross a host boundary, the same
precision trade the reference hooks make (XLA's in-jit ICI all-reduce keeps
its own scheduling).

Run: python examples/by_feature/ddp_comm_hook.py --ddp_comm_hook bf16
"""

import argparse

import torch
from torch.optim.lr_scheduler import LambdaLR

from accelerate_tpu import Accelerator
from accelerate_tpu.utils import set_seed
from accelerate_tpu.utils.dataclasses import DistributedDataParallelKwargs

from _base import load_nlp_example

nlp = load_nlp_example()


def training_function(config, args):
    ddp_kwargs = DistributedDataParallelKwargs(comm_hook=args.ddp_comm_hook)
    accelerator = Accelerator(
        cpu=args.cpu, mixed_precision=args.mixed_precision, kwargs_handlers=[ddp_kwargs]
    )
    set_seed(int(config["seed"]))
    train_dataloader, eval_dataloader = nlp.get_dataloaders(accelerator, int(config["batch_size"]))
    model = nlp.PairClassifier()
    optimizer = torch.optim.AdamW(model.parameters(), lr=config["lr"])
    total_steps = int(config["num_epochs"]) * len(train_dataloader)
    lr_scheduler = LambdaLR(optimizer, lambda step: max(0.0, 1.0 - step / max(total_steps, 1)))

    model, optimizer, train_dataloader, eval_dataloader, lr_scheduler = accelerator.prepare(
        model, optimizer, train_dataloader, eval_dataloader, lr_scheduler
    )

    criterion = torch.nn.CrossEntropyLoss()
    final_accuracy = 0.0
    for epoch in range(int(config["num_epochs"])):
        model.train()
        for batch in train_dataloader:
            logits = model(batch["input_ids_a"], batch["input_ids_b"])
            loss = criterion(logits, batch["labels"])
            accelerator.backward(loss)
            optimizer.step()
            lr_scheduler.step()
            optimizer.zero_grad()

        model.eval()
        correct, total = 0, 0
        for batch in eval_dataloader:
            with torch.no_grad():
                logits = model(batch["input_ids_a"], batch["input_ids_b"])
            preds = torch.argmax(logits, dim=-1)
            preds, refs = accelerator.gather_for_metrics((preds, batch["labels"]))
            correct += int((preds == refs).sum())
            total += len(refs)
        final_accuracy = correct / max(total, 1)
        accelerator.print(
            f"epoch {epoch}: accuracy {final_accuracy:.3f} (comm_hook={args.ddp_comm_hook})"
        )
    return final_accuracy


def main():
    parser = argparse.ArgumentParser(description="DDP comm-hook example")
    parser.add_argument("--mixed_precision", type=str, default=None,
                        choices=["no", "fp16", "bf16", "fp8"])
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--ddp_comm_hook", type=str, default="bf16",
                        choices=["no", "fp16", "bf16"])
    parser.add_argument("--num_epochs", type=int, default=3)
    args = parser.parse_args()
    config = {"lr": 2e-3, "num_epochs": args.num_epochs, "seed": 42, "batch_size": 16}
    training_function(config, args)


if __name__ == "__main__":
    main()
