"""Feature: FSDP training with peak-memory tracking (reference
``examples/by_feature/fsdp_with_peak_mem_tracking.py``).

The reference's ``TorchTracemalloc`` context reads CUDA allocator peaks; the
TPU-native analog reads the device allocator's ``memory_stats()`` (HBM
peak_bytes_in_use) plus host RSS.  The FSDP plugin shards the JAX-native
llama over the ``fsdp`` mesh axis — on N devices the tracked parameter +
optimizer memory drops by ~N vs NO_SHARD, which is the whole point of the
reference's memory benchmark (`tests/fsdp/test_fsdp.py:446-460` bounds).

Run: python examples/by_feature/fsdp_with_peak_mem_tracking.py --fsdp_size 8
"""

import argparse
import gc
import resource

import numpy as np

import jax
import optax

from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.models import llama
from accelerate_tpu.parallel.sharding import data_sharding, make_param_specs, shard_params
from accelerate_tpu.utils import FullyShardedDataParallelPlugin, set_seed


class TPUTracemalloc:
    """Peak device + host memory for the enclosed block."""

    def __enter__(self):
        gc.collect()
        self.begin = self._device_bytes()
        self.host_begin = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        return self

    @staticmethod
    def _device_bytes() -> int:
        try:
            stats = jax.local_devices()[0].memory_stats()
            return int(stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0)))
        except Exception:
            return 0

    def __exit__(self, *exc):
        gc.collect()
        self.peaked = max(0, self._device_bytes() - self.begin)
        self.host_peaked = max(
            0, resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024 - self.host_begin
        )


def training_function(config, args):
    accelerator = Accelerator(
        parallelism_config=ParallelismConfig(fsdp=args.fsdp_size),
        fsdp_plugin=FullyShardedDataParallelPlugin(
            sharding_strategy=args.sharding_strategy,
            cpu_offload=args.cpu_offload,
        ),
    )
    mesh = accelerator.mesh
    set_seed(int(config["seed"]))

    cfg = llama.LlamaConfig.tiny(
        num_layers=int(config["layers"]), hidden_size=int(config["hidden"]), vocab_size=4096
    )

    with TPUTracemalloc() as tracemalloc:
        params = llama.init_params(cfg, jax.random.key(0))
        specs = make_param_specs(
            params, mesh, accelerator.state.fsdp_plugin, rules=llama.PARTITION_RULES
        )
        params = shard_params(params, mesh, specs)
        tx = optax.adamw(config["lr"])
        opt_state = tx.init(params)

        @jax.jit
        def train_step(params, opt_state, batch):
            loss, grads = jax.value_and_grad(llama.loss_fn)(params, batch, cfg)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        rng = np.random.default_rng(0)
        loss = None
        for step in range(args.steps):
            tokens = rng.integers(0, cfg.vocab_size, (8, 64)).astype(np.int32)
            batch = {"input_ids": jax.device_put(tokens, data_sharding(mesh))}
            params, opt_state, loss = train_step(params, opt_state, batch)
        loss = float(jax.device_get(loss))

    accelerator.print(
        f"strategy={args.sharding_strategy} fsdp={dict(mesh.shape).get('fsdp', 1)}: "
        f"device peak {tracemalloc.peaked / 2**20:.1f} MiB, "
        f"host peak {tracemalloc.host_peaked / 2**20:.1f} MiB, final loss {loss:.4f}"
    )
    return tracemalloc.peaked


def main():
    parser = argparse.ArgumentParser(description="FSDP peak-memory example")
    parser.add_argument("--fsdp_size", type=int, default=8)
    parser.add_argument("--sharding_strategy", type=str, default="FULL_SHARD",
                        choices=["FULL_SHARD", "SHARD_GRAD_OP", "NO_SHARD", "HYBRID_SHARD"])
    parser.add_argument("--cpu_offload", action="store_true")
    parser.add_argument("--steps", type=int, default=5)
    args = parser.parse_args()
    config = {"lr": 3e-4, "seed": 42, "layers": 2, "hidden": 64}
    training_function(config, args)


if __name__ == "__main__":
    main()
