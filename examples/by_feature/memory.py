"""Feature: automatic OOM-retry with ``find_executable_batch_size`` (reference
``examples/by_feature/memory.py``).

The decorated inner function re-runs with a halved batch size whenever it
raises an out-of-memory error (torch CUDA OOM / XLA RESOURCE_EXHAUSTED
patterns, utils/memory.py), so one script serves every chip size.

Run: python examples/by_feature/memory.py
"""

import argparse

import torch
from torch.optim.lr_scheduler import LambdaLR

from accelerate_tpu import Accelerator, find_executable_batch_size
from accelerate_tpu.utils import set_seed

from _base import load_nlp_example

nlp = load_nlp_example()


def training_function(config, args):
    accelerator = Accelerator(cpu=args.cpu, mixed_precision=args.mixed_precision)
    set_seed(int(config["seed"]))
    observed_batch_sizes = []

    @find_executable_batch_size(starting_batch_size=int(config["batch_size"]))
    def inner_training_loop(batch_size):
        # Everything that allocates device memory lives INSIDE the decorated
        # function, so a retry starts clean.
        nonlocal observed_batch_sizes
        observed_batch_sizes.append(batch_size)
        accelerator.free_memory()
        train_dataloader, eval_dataloader = nlp.get_dataloaders(accelerator, batch_size)
        model = nlp.PairClassifier()
        optimizer = torch.optim.AdamW(model.parameters(), lr=config["lr"])
        total_steps = int(config["num_epochs"]) * len(train_dataloader)
        lr_scheduler = LambdaLR(optimizer, lambda step: max(0.0, 1.0 - step / max(total_steps, 1)))
        model, optimizer, train_dataloader, eval_dataloader, lr_scheduler = accelerator.prepare(
            model, optimizer, train_dataloader, eval_dataloader, lr_scheduler
        )
        criterion = torch.nn.CrossEntropyLoss()
        final_accuracy = 0.0
        for epoch in range(int(config["num_epochs"])):
            model.train()
            for batch in train_dataloader:
                logits = model(batch["input_ids_a"], batch["input_ids_b"])
                loss = criterion(logits, batch["labels"])
                accelerator.backward(loss)
                optimizer.step()
                lr_scheduler.step()
                optimizer.zero_grad()
            model.eval()
            correct, total = 0, 0
            for batch in eval_dataloader:
                with torch.no_grad():
                    logits = model(batch["input_ids_a"], batch["input_ids_b"])
                preds = torch.argmax(logits, dim=-1)
                preds, refs = accelerator.gather_for_metrics((preds, batch["labels"]))
                correct += int((preds == refs).sum())
                total += len(refs)
            final_accuracy = correct / max(total, 1)
            accelerator.print(f"epoch {epoch}: accuracy {final_accuracy:.3f} (batch {batch_size})")
        return final_accuracy

    acc = inner_training_loop()
    accelerator.print(f"batch sizes tried: {observed_batch_sizes}")
    return acc


def main():
    parser = argparse.ArgumentParser(description="OOM-retry example")
    parser.add_argument("--mixed_precision", type=str, default=None,
                        choices=["no", "fp16", "bf16", "fp8"])
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--num_epochs", type=int, default=3)
    args = parser.parse_args()
    config = {"lr": 2e-3, "num_epochs": args.num_epochs, "seed": 42, "batch_size": 16}
    training_function(config, args)


if __name__ == "__main__":
    main()
