"""Feature: checkpoint/resume with ``save_state``/``load_state`` and mid-epoch
``skip_first_batches`` (reference ``examples/by_feature/checkpointing.py``).

Saves a checkpoint every epoch under ``ProjectConfiguration``'s automatic
naming, then shows resuming: restore the latest checkpoint and skip the
already-consumed batches of the current epoch.

Run: python examples/by_feature/checkpointing.py --checkpointing_steps epoch \
         --project_dir ./ckpt_demo [--resume_from_checkpoint ./ckpt_demo/checkpoints/checkpoint_0]
"""

import argparse
import os

import torch
from torch.optim.lr_scheduler import LambdaLR

from accelerate_tpu import Accelerator, skip_first_batches
from accelerate_tpu.utils import ProjectConfiguration, set_seed

from _base import load_nlp_example

nlp = load_nlp_example()


def training_function(config, args):
    project_config = ProjectConfiguration(
        project_dir=args.project_dir, automatic_checkpoint_naming=True, total_limit=3
    )
    accelerator = Accelerator(
        cpu=args.cpu, mixed_precision=args.mixed_precision, project_config=project_config
    )
    set_seed(int(config["seed"]))
    train_dataloader, eval_dataloader = nlp.get_dataloaders(accelerator, int(config["batch_size"]))
    model = nlp.PairClassifier()
    optimizer = torch.optim.AdamW(model.parameters(), lr=config["lr"])
    total_steps = int(config["num_epochs"]) * len(train_dataloader)
    lr_scheduler = LambdaLR(optimizer, lambda step: max(0.0, 1.0 - step / max(total_steps, 1)))

    model, optimizer, train_dataloader, eval_dataloader, lr_scheduler = accelerator.prepare(
        model, optimizer, train_dataloader, eval_dataloader, lr_scheduler
    )

    starting_epoch = 0
    resume_step = None
    if args.resume_from_checkpoint:
        accelerator.load_state(args.resume_from_checkpoint)
        # Checkpoint name encodes the epoch it was saved after (epoch granularity).
        ckpt_idx = int(os.path.basename(args.resume_from_checkpoint).split("_")[-1])
        starting_epoch = ckpt_idx + 1

    criterion = torch.nn.CrossEntropyLoss()
    overall_step = 0
    final_accuracy = 0.0
    for epoch in range(starting_epoch, int(config["num_epochs"])):
        model.train()
        active_dataloader = train_dataloader
        if resume_step is not None:
            # Mid-epoch resume path: fast-forward the already-consumed batches.
            active_dataloader = skip_first_batches(train_dataloader, resume_step)
            resume_step = None
        for batch in active_dataloader:
            logits = model(batch["input_ids_a"], batch["input_ids_b"])
            loss = criterion(logits, batch["labels"])
            accelerator.backward(loss)
            optimizer.step()
            lr_scheduler.step()
            optimizer.zero_grad()
            overall_step += 1
            if args.checkpointing_steps not in (None, "epoch") and overall_step % int(
                args.checkpointing_steps
            ) == 0:
                accelerator.save_state()
        if args.checkpointing_steps == "epoch":
            accelerator.save_state()

        model.eval()
        correct, total = 0, 0
        for batch in eval_dataloader:
            with torch.no_grad():
                logits = model(batch["input_ids_a"], batch["input_ids_b"])
            preds = torch.argmax(logits, dim=-1)
            preds, refs = accelerator.gather_for_metrics((preds, batch["labels"]))
            correct += int((preds == refs).sum())
            total += len(refs)
        final_accuracy = correct / max(total, 1)
        accelerator.print(f"epoch {epoch}: accuracy {final_accuracy:.3f}")
    return final_accuracy


def main():
    parser = argparse.ArgumentParser(description="Checkpointing example")
    parser.add_argument("--mixed_precision", type=str, default=None,
                        choices=["no", "fp16", "bf16", "fp8"])
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--checkpointing_steps", type=str, default="epoch",
                        help='"epoch", or an integer number of steps')
    parser.add_argument("--project_dir", type=str, default="./ckpt_demo")
    parser.add_argument("--resume_from_checkpoint", type=str, default=None)
    parser.add_argument("--num_epochs", type=int, default=3)
    args = parser.parse_args()
    config = {"lr": 2e-3, "num_epochs": args.num_epochs, "seed": 42, "batch_size": 16}
    training_function(config, args)


if __name__ == "__main__":
    main()
