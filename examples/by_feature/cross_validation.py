"""Feature: k-fold cross validation, aggregating fold predictions across
processes (reference ``examples/by_feature/cross_validation.py``).

Each fold trains a fresh model on k-1 splits and evaluates on the held-out
test split; predictions are ``gather_for_metrics``-ed, and the final metric
averages the folds.

Run: python examples/by_feature/cross_validation.py --num_folds 3
"""

import argparse

import numpy as np
import torch
from torch.optim.lr_scheduler import LambdaLR
from torch.utils.data import DataLoader

from accelerate_tpu import Accelerator
from accelerate_tpu.utils import set_seed

from _base import load_nlp_example

nlp = load_nlp_example()


def get_fold_dataloaders(accelerator, fold: int, num_folds: int, batch_size: int):
    """Split the training set into ``num_folds``; train on k-1, validate on the
    held-out fold, test on the canonical validation set."""
    data = nlp.make_dataset(512, seed=0)
    folds = np.array_split(np.arange(len(data)), num_folds)
    heldout = set(folds[fold].tolist())
    train = [s for i, s in enumerate(data) if i not in heldout]
    valid = [s for i, s in enumerate(data) if i in heldout]
    test = nlp.make_dataset(128, seed=1)
    return (
        DataLoader(train, shuffle=True, collate_fn=nlp.collate, batch_size=batch_size),
        DataLoader(valid, shuffle=False, collate_fn=nlp.collate, batch_size=nlp.EVAL_BATCH_SIZE),
        DataLoader(test, shuffle=False, collate_fn=nlp.collate, batch_size=nlp.EVAL_BATCH_SIZE),
    )


def training_function(config, args):
    accelerator = Accelerator(cpu=args.cpu, mixed_precision=args.mixed_precision)
    set_seed(int(config["seed"]))
    criterion = torch.nn.CrossEntropyLoss()
    test_fold_logits = []
    test_refs = None

    for fold in range(args.num_folds):
        train_dl, valid_dl, test_dl = get_fold_dataloaders(
            accelerator, fold, args.num_folds, int(config["batch_size"])
        )
        model = nlp.PairClassifier()
        optimizer = torch.optim.AdamW(model.parameters(), lr=config["lr"])
        total_steps = int(config["num_epochs"]) * len(train_dl)
        lr_scheduler = LambdaLR(optimizer, lambda step: max(0.0, 1.0 - step / max(total_steps, 1)))
        model, optimizer, train_dl, valid_dl, test_dl, lr_scheduler = accelerator.prepare(
            model, optimizer, train_dl, valid_dl, test_dl, lr_scheduler
        )

        for epoch in range(int(config["num_epochs"])):
            model.train()
            for batch in train_dl:
                logits = model(batch["input_ids_a"], batch["input_ids_b"])
                loss = criterion(logits, batch["labels"])
                accelerator.backward(loss)
                optimizer.step()
                lr_scheduler.step()
                optimizer.zero_grad()

        # Held-out fold metric (monitoring only).
        model.eval()
        correct, total = 0, 0
        for batch in valid_dl:
            with torch.no_grad():
                logits = model(batch["input_ids_a"], batch["input_ids_b"])
            preds = torch.argmax(logits, dim=-1)
            preds, refs = accelerator.gather_for_metrics((preds, batch["labels"]))
            correct += int((preds == refs).sum())
            total += len(refs)
        accelerator.print(f"fold {fold}: heldout accuracy {correct / max(total, 1):.3f}")

        # Accumulate test-set logits for the ensemble metric.
        fold_logits, fold_refs = [], []
        for batch in test_dl:
            with torch.no_grad():
                logits = model(batch["input_ids_a"], batch["input_ids_b"])
            logits, refs = accelerator.gather_for_metrics((logits, batch["labels"]))
            fold_logits.append(logits.float())
            fold_refs.append(refs)
        test_fold_logits.append(torch.cat(fold_logits))
        test_refs = torch.cat(fold_refs)
        accelerator.free_memory()

    # Ensemble: average fold logits, then score.
    ensemble = torch.stack(test_fold_logits).mean(dim=0)
    preds = torch.argmax(ensemble, dim=-1)
    accuracy = float((preds == test_refs).float().mean())
    accelerator.print(f"ensemble test accuracy over {args.num_folds} folds: {accuracy:.3f}")
    return accuracy


def main():
    parser = argparse.ArgumentParser(description="Cross-validation example")
    parser.add_argument("--mixed_precision", type=str, default=None,
                        choices=["no", "fp16", "bf16", "fp8"])
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--num_folds", type=int, default=3)
    parser.add_argument("--num_epochs", type=int, default=2)
    args = parser.parse_args()
    config = {"lr": 2e-3, "num_epochs": args.num_epochs, "seed": 42, "batch_size": 16}
    training_function(config, args)


if __name__ == "__main__":
    main()
