"""Feature: coordinated early stopping with ``set_trigger``/``check_trigger``
(reference ``examples/by_feature/early_stopping.py``).

Any process may raise the trigger (here: loss below a threshold); the check is
an all-reduce, so EVERY process sees it and breaks on the same step — no
deadlocked collective with half the replicas still in the loop.

Run: python examples/by_feature/early_stopping.py
"""

import argparse

import torch
from torch.optim.lr_scheduler import LambdaLR

from accelerate_tpu import Accelerator
from accelerate_tpu.utils import set_seed

from _base import load_nlp_example

nlp = load_nlp_example()


class EarlyStoppingCallback:
    """Raise the breakpoint trigger once the loss stays under ``threshold``."""

    def __init__(self, threshold: float = 0.25, patience: int = 3):
        self.threshold = threshold
        self.patience = patience
        self.count = 0

    def check_early_stopping(self, loss: float) -> bool:
        self.count = self.count + 1 if loss < self.threshold else 0
        return self.count >= self.patience


def training_function(config, args):
    accelerator = Accelerator(cpu=args.cpu, mixed_precision=args.mixed_precision)
    set_seed(int(config["seed"]))
    train_dataloader, eval_dataloader = nlp.get_dataloaders(accelerator, int(config["batch_size"]))
    model = nlp.PairClassifier()
    optimizer = torch.optim.AdamW(model.parameters(), lr=config["lr"])
    total_steps = int(config["num_epochs"]) * len(train_dataloader)
    lr_scheduler = LambdaLR(optimizer, lambda step: max(0.0, 1.0 - step / max(total_steps, 1)))

    model, optimizer, train_dataloader, eval_dataloader, lr_scheduler = accelerator.prepare(
        model, optimizer, train_dataloader, eval_dataloader, lr_scheduler
    )

    callback = EarlyStoppingCallback(threshold=0.25)
    criterion = torch.nn.CrossEntropyLoss()
    stopped_at = None
    step = 0
    for epoch in range(int(config["num_epochs"])):
        model.train()
        for batch in train_dataloader:
            logits = model(batch["input_ids_a"], batch["input_ids_b"])
            loss = criterion(logits, batch["labels"])
            accelerator.backward(loss)
            # This process votes to stop...
            if callback.check_early_stopping(float(loss.detach())):
                accelerator.set_trigger()
            optimizer.step()
            lr_scheduler.step()
            optimizer.zero_grad()
            step += 1
            # ...and ALL processes agree via the all-reduced trigger.
            if accelerator.check_trigger():
                stopped_at = step
                break
        if stopped_at is not None:
            break
    accelerator.print(
        f"stopped early at step {stopped_at}" if stopped_at else "ran to completion"
    )
    return stopped_at


def main():
    parser = argparse.ArgumentParser(description="Early-stopping example")
    parser.add_argument("--mixed_precision", type=str, default=None,
                        choices=["no", "fp16", "bf16", "fp8"])
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--num_epochs", type=int, default=5)
    args = parser.parse_args()
    config = {"lr": 2e-3, "num_epochs": args.num_epochs, "seed": 42, "batch_size": 16}
    training_function(config, args)


if __name__ == "__main__":
    main()
