"""Feature: Megatron-LM-style GPT pretraining via the Megatron config dialect
(reference ``examples/by_feature/megatron_lm_gpt_pretraining.py``).

The reference hands the model to the Megatron engine; here
``MegatronLMPlugin(tp_degree, pp_degree, num_micro_batches,
use_distributed_optimizer, sequence_parallelism)`` is mapped onto the SAME
named mesh every other strategy uses (tp/pp axes, distributed optimizer →
fsdp axis, sequence_parallelism → sp axis) and the GPT-2 family model trains
under one jit-compiled step — no engine handoff.

Run: python examples/by_feature/megatron_lm_gpt_pretraining.py --tp_degree 2 --pp_degree 1
"""

import argparse
import time

import numpy as np

import jax
import optax

from accelerate_tpu import Accelerator
from accelerate_tpu.models import gpt2
from accelerate_tpu.parallel.sharding import data_sharding, make_param_specs, shard_params
from accelerate_tpu.utils import set_seed
from accelerate_tpu.utils.megatron import MegatronLMPlugin


def training_function(config, args):
    plugin = MegatronLMPlugin(
        tp_degree=args.tp_degree,
        pp_degree=args.pp_degree,
        num_micro_batches=args.num_micro_batches,
        use_distributed_optimizer=args.use_distributed_optimizer,
        sequence_parallelism=args.sequence_parallelism,
    )
    accelerator = Accelerator(megatron_lm_plugin=plugin)
    mesh = accelerator.mesh
    accelerator.print(f"megatron dialect mesh: {dict(mesh.shape)}")
    set_seed(int(config["seed"]))

    cfg = gpt2.GPT2Config.tiny(
        num_layers=int(config["layers"]), hidden_size=int(config["hidden"]), vocab_size=4096
    )
    params = gpt2.init_params(cfg, jax.random.key(0))
    specs = make_param_specs(
        params, mesh, accelerator.state.fsdp_plugin, rules=gpt2.PARTITION_RULES
    )
    params = shard_params(params, mesh, specs)

    tx = optax.adamw(config["lr"])
    opt_state = tx.init(params)

    @jax.jit
    def train_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(gpt2.loss_fn)(params, batch, cfg)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    rng = np.random.default_rng(0)
    t0 = time.perf_counter()
    loss = None
    for step in range(args.steps):
        tokens = rng.integers(0, cfg.vocab_size, (args.batch_size, args.seq_len)).astype(np.int32)
        batch = {"input_ids": jax.device_put(tokens, data_sharding(mesh))}
        params, opt_state, loss = train_step(params, opt_state, batch)
        if step % 5 == 0 or step == args.steps - 1:
            accelerator.print(f"step {step}: loss {float(jax.device_get(loss)):.4f}")
    dt = time.perf_counter() - t0
    tok = args.steps * args.batch_size * args.seq_len
    accelerator.print(f"{tok / dt:.0f} tokens/s (incl. compile)")
    return float(jax.device_get(loss))


def main():
    parser = argparse.ArgumentParser(description="Megatron-dialect GPT pretraining")
    parser.add_argument("--tp_degree", type=int, default=2)
    parser.add_argument("--pp_degree", type=int, default=1)
    parser.add_argument("--num_micro_batches", type=int, default=1)
    parser.add_argument("--use_distributed_optimizer", action="store_true")
    parser.add_argument("--sequence_parallelism", action="store_true")
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--seq_len", type=int, default=64)
    args = parser.parse_args()
    config = {"lr": 3e-4, "seed": 42, "layers": 2, "hidden": 64}
    training_function(config, args)


if __name__ == "__main__":
    main()
