"""Feature: device profiling with ``accelerator.profile()`` (reference
``examples/by_feature/profiler.py``).

The reference exports torch.profiler Chrome traces; here the same
``ProfileKwargs`` surface drives ``jax.profiler`` — the trace under
``output_trace_dir/profile_<rank>`` opens in Perfetto/TensorBoard and shows
the compiled step's MXU utilization and HBM transfers.

Run: python examples/by_feature/profiler.py --output_trace_dir ./profile_demo
"""

import argparse

import torch
from torch.optim.lr_scheduler import LambdaLR

from accelerate_tpu import Accelerator
from accelerate_tpu.utils import ProfileKwargs, set_seed

from _base import load_nlp_example

nlp = load_nlp_example()


def training_function(config, args):
    profile_kwargs = ProfileKwargs(output_trace_dir=args.output_trace_dir)
    accelerator = Accelerator(
        cpu=args.cpu, mixed_precision=args.mixed_precision, kwargs_handlers=[profile_kwargs]
    )
    set_seed(int(config["seed"]))
    train_dataloader, eval_dataloader = nlp.get_dataloaders(accelerator, int(config["batch_size"]))
    model = nlp.PairClassifier()
    optimizer = torch.optim.AdamW(model.parameters(), lr=config["lr"])
    total_steps = int(config["num_epochs"]) * len(train_dataloader)
    lr_scheduler = LambdaLR(optimizer, lambda step: max(0.0, 1.0 - step / max(total_steps, 1)))

    model, optimizer, train_dataloader, eval_dataloader, lr_scheduler = accelerator.prepare(
        model, optimizer, train_dataloader, eval_dataloader, lr_scheduler
    )

    criterion = torch.nn.CrossEntropyLoss()
    # Profile one epoch of training steps.
    with accelerator.profile() as prof:
        model.train()
        for batch in train_dataloader:
            logits = model(batch["input_ids_a"], batch["input_ids_b"])
            loss = criterion(logits, batch["labels"])
            accelerator.backward(loss)
            optimizer.step()
            lr_scheduler.step()
            optimizer.zero_grad()
    if args.output_trace_dir:
        accelerator.print(f"trace written under {args.output_trace_dir}")
    return prof


def main():
    parser = argparse.ArgumentParser(description="Profiler example")
    parser.add_argument("--mixed_precision", type=str, default=None,
                        choices=["no", "fp16", "bf16", "fp8"])
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--output_trace_dir", type=str, default=None)
    parser.add_argument("--num_epochs", type=int, default=1)
    args = parser.parse_args()
    config = {"lr": 2e-3, "num_epochs": args.num_epochs, "seed": 42, "batch_size": 16}
    training_function(config, args)


if __name__ == "__main__":
    main()
