"""Prints the Accelerator state produced by the current config/env — the
reference's `run_me.py` smoke payload for every template in this folder."""

from accelerate_tpu import Accelerator

accelerator = Accelerator()
accelerator.print(f"Accelerator state from the current environment:\n{accelerator.state}")
accelerator.end_training()
