"""Complete NLP training example — the repo's analog of the reference
``examples/complete_nlp_example.py`` (324 LoC): the canonical ``nlp_example``
plus EVERY production knob in one script — experiment tracking, step- or
epoch-granular checkpointing, full resume (including mid-epoch
``skip_first_batches``), gradient accumulation, and CLI control of all of it.

Run:
  python examples/complete_nlp_example.py --checkpointing_steps epoch \
      --with_tracking --project_dir ./complete_nlp
  python examples/complete_nlp_example.py \
      --resume_from_checkpoint ./complete_nlp/checkpoints/checkpoint_0
"""

import argparse
import os

import torch
from torch.optim.lr_scheduler import LambdaLR

from accelerate_tpu import Accelerator, skip_first_batches
from accelerate_tpu.utils import ProjectConfiguration, set_seed

import importlib.util as _ilu

_spec = _ilu.spec_from_file_location(
    "nlp_example", os.path.join(os.path.dirname(os.path.abspath(__file__)), "nlp_example.py")
)
nlp = _ilu.module_from_spec(_spec)
_spec.loader.exec_module(nlp)


def training_function(config, args):
    project_config = ProjectConfiguration(
        project_dir=args.project_dir, automatic_checkpoint_naming=True, total_limit=3
    )
    accelerator = Accelerator(
        cpu=args.cpu,
        mixed_precision=args.mixed_precision,
        gradient_accumulation_steps=args.gradient_accumulation_steps,
        log_with="generic" if args.with_tracking else None,
        project_config=project_config,
    )
    if args.with_tracking:
        accelerator.init_trackers("complete_nlp_example", config)

    set_seed(int(config["seed"]))
    train_dataloader, eval_dataloader = nlp.get_dataloaders(accelerator, int(config["batch_size"]))
    model = nlp.PairClassifier()
    optimizer = torch.optim.AdamW(model.parameters(), lr=config["lr"])
    total_steps = int(config["num_epochs"]) * len(train_dataloader)
    lr_scheduler = LambdaLR(optimizer, lambda step: max(0.0, 1.0 - step / max(total_steps, 1)))

    model, optimizer, train_dataloader, eval_dataloader, lr_scheduler = accelerator.prepare(
        model, optimizer, train_dataloader, eval_dataloader, lr_scheduler
    )

    # Resume bookkeeping (reference complete_nlp_example.py): checkpoint names
    # encode granularity — epoch_{n} dirs resume at epoch n+1, step saves
    # resume mid-epoch via skip_first_batches.
    starting_epoch = 0
    resume_step = None
    if args.resume_from_checkpoint:
        accelerator.load_state(args.resume_from_checkpoint)
        name = os.path.basename(os.path.normpath(args.resume_from_checkpoint))
        ckpt_idx = int(name.split("_")[-1])
        if args.checkpointing_steps == "epoch" or args.checkpointing_steps is None:
            starting_epoch = ckpt_idx + 1
        else:
            step_every = int(args.checkpointing_steps)
            consumed = (ckpt_idx + 1) * step_every
            starting_epoch = consumed // len(train_dataloader)
            resume_step = consumed % len(train_dataloader)

    criterion = torch.nn.CrossEntropyLoss()
    overall_step = 0
    final_accuracy = 0.0
    for epoch in range(starting_epoch, int(config["num_epochs"])):
        model.train()
        total_loss = 0.0
        active_dataloader = train_dataloader
        if resume_step is not None:
            active_dataloader = skip_first_batches(train_dataloader, resume_step)
            overall_step += resume_step
            resume_step = None
        for batch in active_dataloader:
            with accelerator.accumulate(model):
                outputs = model(input_ids_a=batch["input_ids_a"], input_ids_b=batch["input_ids_b"])
                loss = criterion(outputs.logits if hasattr(outputs, "logits") else outputs, batch["labels"])
                total_loss += float(loss.detach())
                accelerator.backward(loss)
                optimizer.step()
                lr_scheduler.step()
                optimizer.zero_grad()
            overall_step += 1
            if isinstance(args.checkpointing_steps, str) and args.checkpointing_steps.isdigit():
                if overall_step % int(args.checkpointing_steps) == 0:
                    accelerator.save_state()
        if args.checkpointing_steps == "epoch":
            accelerator.save_state()

        model.eval()
        correct = total = 0
        for batch in eval_dataloader:
            with torch.no_grad():
                outputs = model(input_ids_a=batch["input_ids_a"], input_ids_b=batch["input_ids_b"])
            logits = outputs.logits if hasattr(outputs, "logits") else outputs
            predictions = logits.argmax(dim=-1)
            predictions, references = accelerator.gather_for_metrics((predictions, batch["labels"]))
            correct += int((predictions == references).sum())
            total += int(references.numel())
        final_accuracy = correct / max(total, 1)
        accelerator.print(f"epoch {epoch}: accuracy={final_accuracy:.3f}")
        if args.with_tracking:
            accelerator.log(
                {
                    "accuracy": final_accuracy,
                    "train_loss": total_loss / max(len(train_dataloader), 1),
                    "epoch": epoch,
                },
                step=epoch,
            )

    if args.with_tracking:
        accelerator.end_training()
    return final_accuracy


def main():
    parser = argparse.ArgumentParser(description="Complete NLP training example")
    parser.add_argument("--mixed_precision", type=str, default=None, choices=["no", "fp16", "bf16", "fp8"])
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--checkpointing_steps", type=str, default=None,
                        help="'epoch', or an integer number of steps between saves")
    parser.add_argument("--resume_from_checkpoint", type=str, default=None)
    parser.add_argument("--with_tracking", action="store_true")
    parser.add_argument("--project_dir", type=str, default="./complete_nlp")
    parser.add_argument("--gradient_accumulation_steps", type=int, default=1)
    parser.add_argument("--num_epochs", type=int, default=3)
    args = parser.parse_args()
    config = {"lr": 2e-3, "num_epochs": args.num_epochs, "seed": 42, "batch_size": 16}
    training_function(config, args)


if __name__ == "__main__":
    main()
