"""Benchmark: llama training throughput + MFU on the available TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline (BASELINE.md): ≥45% MFU for Llama-family FSDP training on v5e —
``vs_baseline`` is achieved-MFU / 0.45.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _peak_flops_per_chip() -> float:
    """bf16 peak per chip (v5e: 197 TFLOP/s) — the table lives in the telemetry
    subsystem so the live MFU gauge and this benchmark can never disagree."""
    from accelerate_tpu.telemetry import peak_flops_per_chip

    return peak_flops_per_chip()


def _run(
    cfg_name: str,
    d: int,
    layers: int,
    f: int,
    batch: int,
    seq: int,
    attention_impl: str = "flash",
    remat_policy: str = "dots",
    loss_impl: str = "dense",
    param_dtype: str = "f32",
    vocab_size: int = 32000,
    host_opt: bool = False,
):
    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu.models import llama
    from accelerate_tpu.telemetry import CompileWatcher

    # Counts XLA backend compiles (jit cache misses) for the telemetry block
    # of the result line; warmup compiles are expected, steady-state ones are
    # the recompile bug the count exists to expose.
    compile_watcher = CompileWatcher()

    cfg = llama.LlamaConfig(
        vocab_size=vocab_size,
        hidden_size=d,
        intermediate_size=f,
        num_layers=layers,
        num_heads=max(d // 128, 1),
        num_kv_heads=max(d // 256, 1),
        max_seq_len=seq,
        remat=True,
        # Flash attention keeps score tiles out of HBM, which lets the remat
        # policy save matmul outputs ("dots") instead of recomputing the whole
        # layer — measured +3.4 MFU points over einsum+nothing_saveable on v5e.
        attention_impl=attention_impl,
        remat_policy=remat_policy,
        # "chunked" streams the LM-head loss over vocab tiles — removes the
        # [B,S,32000] fp32 logits (+cotangent) HBM spike entirely.
        loss_impl=loss_impl,
        # "bf16" = pure bf16 params, no fp32 master (the reference's
        # downcast_bf16 TPU semantics): halves param/grad HBM traffic —
        # measured +2.8 MFU points on v5e.  AdamW moments follow the param
        # dtype; fp32-master rungs below are the precision-conservative path.
        param_dtype=jnp.bfloat16 if param_dtype == "bf16" else jnp.float32,
    )
    params = llama.init_params(cfg, jax.random.key(0))
    tx = optax.adamw(1e-4)
    if host_opt:
        # ZeRO-offload rung: AdamW moments live in pinned host memory and ride
        # explicit H2D/D2H transfers inside the step — frees ~4N bytes of HBM
        # (the moments) at the cost of per-step host-link traffic.
        from accelerate_tpu.parallel.host_offload import host_offload

        tx = host_offload(tx)
    opt_state = tx.init(params)
    tokens = np.random.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    batch_tree = {"input_ids": jnp.asarray(tokens)}

    import functools

    def _step(params, opt_state, batch_tree):
        loss, grads = jax.value_and_grad(llama.loss_fn)(params, batch_tree, cfg)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # Donation matters: without it every step copies params+opt state (~45 ms
    # and 2x transient HBM at this size).
    if host_opt and jax.default_backend() == "tpu":
        # The carried opt state must come back in host memory — pin the out
        # shardings so the donated pinned_host buffers are reused instead of
        # clashing with a default device-placed output.
        opt_sh = jax.tree_util.tree_map(
            lambda x: x.sharding if isinstance(x, jax.Array) else None, opt_state
        )
        train_step = jax.jit(
            _step, donate_argnums=(0, 1), out_shardings=(None, opt_sh, None)
        )
    elif host_opt:
        # CPU smoke path: the backend cannot execute D2H placement inside jit,
        # so the state silently returns in device memory — numerics identical,
        # placement untested here (the TPU rung is the real measurement).
        # Donating the pinned_host input against a device output would crash;
        # donate params only.
        train_step = jax.jit(_step, donate_argnums=(0,))
    else:
        train_step = jax.jit(_step, donate_argnums=(0, 1))

    # AOT lower+compile so the SAME executable both runs the timed loop and
    # feeds the compiled-program inspector (cost/memory analysis + comms
    # ledger) — analysis is free, no second compile of the program.
    compiled_step = train_step.lower(params, opt_state, batch_tree).compile()

    # Warmup.  NOTE: sync via device_get — block_until_ready does not
    # reliably block on tunneled platforms.
    for _ in range(3):
        params, opt_state, loss = compiled_step(params, opt_state, batch_tree)
    jax.device_get(loss)
    warmup_compiles = compile_watcher.count

    n_steps = 20
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(n_steps):
            params, opt_state, loss = compiled_step(params, opt_state, batch_tree)
        jax.device_get(loss)
        best = min(best, (time.perf_counter() - t0) / n_steps)
    dt = best

    tokens_per_step = batch * seq
    n_params = cfg.num_params()
    # 6ND matmul FLOPs + 12*L*d*T*S causal-attention term (/2 for causal).
    attn_flops = 12 * layers * d * seq * seq * batch / 2
    flops_per_step = 6.0 * n_params * tokens_per_step + attn_flops
    mfu = flops_per_step / dt / _peak_flops_per_chip() / jax.device_count()
    out = {
        "config": cfg_name,
        "params": n_params,
        "tokens_per_sec": tokens_per_step / dt,
        "step_ms": dt * 1e3,
        "mfu": mfu,
        "loss": float(loss),
    }
    try:  # peak HBM, where the backend exposes it (not all tunnels do)
        stats = jax.local_devices()[0].memory_stats() or {}
        if "peak_bytes_in_use" in stats:
            out["peak_hbm_gb"] = round(stats["peak_bytes_in_use"] / 1e9, 2)
    except Exception:
        pass
    compile_watcher.stop()
    # Telemetry snapshot for the result line: total/steady-state compile
    # counts (steady-state > 0 means the timed loop itself recompiled — a
    # perf bug), mean step time, and peak HBM where available.
    out["telemetry"] = {
        "compile_count": compile_watcher.count,
        "steady_state_compiles": compile_watcher.count - warmup_compiles,
        "compile_ms": round(compile_watcher.total_ms, 1),
        "mean_step_ms": round(dt * 1e3, 3),
        "peak_hbm_gb": out.get("peak_hbm_gb"),
    }
    # Comms/memory block from the compiled-program inspector: XLA-analyzed
    # FLOPs/bytes, the HBM breakdown, and the collective ledger.  mfu_measured
    # is achieved MFU against the ANALYZED cost — when it diverges from the
    # 6ND-estimate headline, the estimate (not the hardware) is off.  Pure
    # analysis of the already-compiled executable; never fails a rung.
    try:
        from accelerate_tpu.telemetry import inspect_compiled

        report = inspect_compiled(compiled_step, name=cfg_name)
        out["introspect"] = {
            "flops": report.flops,
            "bytes_accessed": report.bytes_accessed,
            "memory": report.memory,
            "comms": report.ledger.to_dict(),
            "comms_compute_ratio": report.comms_compute_ratio,
        }
        if report.flops:
            out["introspect"]["mfu_measured"] = round(
                report.flops / dt / _peak_flops_per_chip() / jax.device_count(), 4
            )
    except Exception as e:
        out["introspect"] = {"error": str(e)[:200]}
    return out


LADDER = [
    # Rung 0: llama3-style 128k vocabulary (d2048/L6/f8192, 903M params) at
    # dense/b6 — 0.8462 MFU measured r4 on v5e: the [B*S, d] x [d, 128256]
    # head matmul is the most MXU-efficient op in the model, so the realistic
    # modern vocab size RAISES MFU over the 32k-vocab rungs.  b8 OOMs; the
    # full dense-vs-chunked table at this vocab is BENCH_chunked_128k.json.
    ("llama3-903m-v128k", 2048, 6, 8192, 6, 2048, "pallas", "dots", "dense", "bf16", 128256),
    ("llama3-903m-v128k", 2048, 6, 8192, 4, 2048, "pallas", "dots", "dense", "bf16", 128256),
    # Next rungs: pure-bf16 params (reference downcast_bf16 TPU semantics) at
    # the batch the freed HBM admits — 0.6757 MFU measured r3 on v5e at b10
    # (b8 0.6632, b12 0.6644; fp32-master can't fit b10).  Then b8 bf16.
    # Rung 2: the fp32-master path — 0.6353 MFU driver-verifiable with the
    # 1024 attention block (0.6041 at block 512, BENCH_opportunistic.json;
    # 0.5202 at block 256; 2048 = one-block OOMs VMEM).  An unmeasured
    # variant must never shadow a proven one (the ladder stops at the first
    # success).  Later rungs are conservative fallbacks (einsum attention,
    # full remat) then smaller models.
    ("llama-509m", 2048, 6, 8192, 10, 2048, "pallas", "dots", "dense", "bf16"),
    ("llama-509m", 2048, 6, 8192, 8, 2048, "pallas", "dots", "dense", "bf16"),
    # batch 8 measured +0.7 MFU points over batch 4 on v5e (0.604 vs
    # 0.597); 10/12/16 fail to compile (HBM) with the dense loss; seq 4096
    # reaches 0.6152 at b4/blk1024 (was worse at blk512) and flash loses.
    # Chunked-vocab CE measured r3: b8 0.5863 / b10 0.5790 at blk512, 0.6161
    # at b8/blk1024; b12/s4096 OOM, and b16/chunked/bf16 also OOMs — loses at
    # every feasible shape here (see docs/concept_guides/performance.md #5), so dense stays
    # the winning loss impl.  remat "nothing" at b8
    # also measured r3: 0.5711 — saving every activation costs more HBM
    # traffic than "dots" recomputes.
    ("llama-509m", 2048, 6, 8192, 8, 2048, "pallas", "dots", "dense"),
    ("llama-509m", 2048, 6, 8192, 4, 2048, "pallas", "dots", "dense"),
    ("llama-509m", 2048, 6, 8192, 4, 2048, "flash", "dots", "dense"),
    ("llama-509m", 2048, 6, 8192, 4, 2048, "einsum", "nothing", "dense"),
    ("llama-310m", 1536, 6, 6144, 4, 2048, "einsum", "nothing", "dense"),
    ("llama-128m", 1024, 4, 4096, 4, 1024, "einsum", "nothing", "dense"),
]

# Opt-in candidates (unmeasured on hardware; a failed remote compile can wedge
# the device tunnel, so bigger batches must be requested explicitly):
# BENCH_TRY_CHUNKED=1 leads with the chunked-vocab loss at the proven batch —
# remat'd scan removes the [B,S,V] logits (+cotangent) HBM spike
# (ops/chunked_ce.py); BENCH_TRY_BIG=1 additionally tries the larger batch
# that freed HBM may admit.
if os.environ.get("BENCH_TRY_CHUNKED") or os.environ.get("BENCH_TRY_BIG"):
    LADDER.insert(0, ("llama-509m", 2048, 6, 8192, 8, 2048, "pallas", "dots", "chunked"))
if os.environ.get("BENCH_TRY_BIG"):
    LADDER.insert(0, ("llama-509m", 2048, 6, 8192, 12, 2048, "pallas", "dots", "chunked"))

# Proof rungs where parameter HBM pressure binds (VERDICT r3 item 1): a 1.39B
# llama on one v5e — bf16 params (2.78G) + AdamW moments (5.56G) + grads
# (2.78G) leave ~4.6G for activations, so batch 2 with "dots" remat is the
# frontier (batch 3 OOMs: 16.40G of 15.75G, measured r4).  Measured r4 ladder:
# b2/dots/dense 0.6092, b2/dots/chunked 0.5947, b4/nothing 0.5890,
# b8/nothing/chunked 0.5654, b1/s4096 0.5530.  These run AFTER the headline
# rung and are attached to the result's detail — proving MFU >= 0.60 where
# HBM binds without shadowing the 509m champion headline.
PROOF_RUNGS = [
    ("llama-1.4b", 2048, 20, 8192, 2, 2048, "pallas", "dots", "dense", "bf16"),
    ("llama-1.4b", 2048, 20, 8192, 2, 2048, "pallas", "dots", "chunked", "bf16"),
    ("llama-1.4b", 2048, 20, 8192, 4, 2048, "pallas", "nothing", "dense", "bf16"),
]

# Opt-in (unmeasured): host-offloaded AdamW moments free ~5.6G of HBM at 1.39B
# — enough for batch 3-4 where batch 2 was the dense frontier — IF the ~11GB
# per-step host-link round-trip hides behind the longer step.  Never shadows
# the proven rungs without the flag.
if os.environ.get("BENCH_TRY_HOSTOPT"):
    PROOF_RUNGS.insert(
        0, ("llama-1.4b-hostopt", 2048, 20, 8192, 4, 2048, "pallas", "dots", "dense", "bf16", 32000, True)
    )
    PROOF_RUNGS.insert(
        1, ("llama-1.4b-hostopt", 2048, 20, 8192, 3, 2048, "pallas", "dots", "dense", "bf16", 32000, True)
    )

# Frontier rungs: unmeasured candidates that run AFTER the headline and proof
# have landed, so they can never shadow a proven number — pure information.
# Every outcome is attached to detail.frontier and appended incrementally to
# BENCH_frontier_live.json (survives a mid-run kill).  Wall-clock bounded by
# BENCH_FRONTIER_BUDGET_S.
#
# The round-5 candidates were all MEASURED when the tunnel revived
# (BENCH_frontier_live.json): 128k-vocab b7 = 0.8207 MFU (b6 = 0.8454 stays
# champion), 1.39B host-offloaded-moments b4 = 0.297 MFU (transfer-bound — see
# docs/concept_guides/performance.md), b3 hit its 480 s rung budget.  The list
# is empty until there is a new unmeasured candidate; re-running known numbers
# at driver time costs ~20 min and a rung-timeout wedge risk for no
# information.  BENCH_FRONTIER_JSON still injects ad-hoc rungs.
FRONTIER_RUNGS = []

# Test hook: lets the smoke tests exercise the rung-subprocess machinery with
# CPU-sized configs (a real rung takes minutes on CPU).
if os.environ.get("BENCH_LADDER_JSON"):
    LADDER = [tuple(r) for r in json.loads(os.environ["BENCH_LADDER_JSON"])]
    PROOF_RUNGS = []
    FRONTIER_RUNGS = []
if os.environ.get("BENCH_PROOF_LADDER_JSON"):
    PROOF_RUNGS = [tuple(r) for r in json.loads(os.environ["BENCH_PROOF_LADDER_JSON"])]
if os.environ.get("BENCH_FRONTIER_JSON"):
    FRONTIER_RUNGS = [tuple(r) for r in json.loads(os.environ["BENCH_FRONTIER_JSON"])]


def _run_rung_subprocess(rung_index: int, timeout_s: int, flag: str = "--rung"):
    """Run one ladder rung in a bounded subprocess.

    A half-up device tunnel can hang a compile inside a C call, where neither
    SIGALRM nor Python-level timeouts fire — the subprocess boundary is the
    only real timeout.  BUT a SIGKILL delivered mid-compile wedges the tunnel
    for >15 min (observed r4), so the escalation is cooperative: SIGTERM
    first (lets Python unwind and the XLA client shut down when it is not
    stuck in C), a grace period, and SIGKILL only as the last resort.
    Returns (result_dict | None, error_str | None)."""
    import subprocess

    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), flag, str(rung_index)],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.terminate()  # cooperative: compile clients get to shut down
        try:
            stdout, stderr = proc.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()  # stuck inside a C call; nothing else works
            proc.communicate()
            return None, f"timeout after {timeout_s}s (SIGKILL after 60s grace)"
        if proc.returncode != 0:
            return None, f"timeout after {timeout_s}s (exited on SIGTERM)"
        # The child finished right at the deadline (exit 0 with a result on
        # stdout): fall through and parse it rather than discard a valid
        # measurement and burn a reacquire + retry.
        return None, (stderr or "")[-200:].replace("\n", " ")
    # Scan from the end for the LAST parseable JSON line — spurious
    # brace-prefixed library output (before or after the result) is skipped.
    for line in reversed(stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), None
            except ValueError:
                continue
    return None, "no parseable result line"


class _PartialResults:
    """Per-rung partial-result checkpointing through the resilience manifest.

    4 of 5 bench rounds died to device flake; when the *process* dies too
    (SIGKILL, OOM killer, machine loss — the cases the emergency-JSON
    watchdog cannot catch), every completed rung measurement died with it.
    After every successful rung the current best result is published to
    ``BENCH_partial/`` as a manifest-verified directory (same staging + atomic
    swap + retry policy as training checkpoints), so a mid-bench death leaves
    the best completed rung on disk: the emergency path reads it back, and a
    human (or the next round) finds ``BENCH_partial/result.json`` with a
    manifest certifying it is complete, not a torn write."""

    def __init__(self, root: str = "BENCH_partial"):
        self.root = root

    def clear(self):
        """Fresh round: a stale partial from an older run must not masquerade
        as this round's measurement."""
        import shutil

        for suffix in ("", ".tmp", ".old"):
            shutil.rmtree(self.root + suffix, ignore_errors=True)

    def publish(self, payload: dict):
        import shutil

        from accelerate_tpu.resilience.manifest import write_manifest
        from accelerate_tpu.resilience.retry import retrying

        def _io():
            tmp = f"{self.root}.tmp"
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp)
            with open(os.path.join(tmp, "result.json"), "w") as f:
                json.dump(payload, f)
            write_manifest(tmp)
            # Same displaced-old swap as checkpoint publish: the previous
            # partial stays readable until the new one is fully in place.
            old = f"{self.root}.old"
            if os.path.isdir(old):
                shutil.rmtree(old)
            displaced = False
            if os.path.isdir(self.root):
                os.rename(self.root, old)
                displaced = True
            try:
                os.rename(tmp, self.root)
            except BaseException:
                if displaced:
                    os.rename(old, self.root)
                raise
            if displaced:
                shutil.rmtree(old, ignore_errors=True)

        try:
            retrying(label="bench.partial", tries=3, deadline_s=30.0).call(_io)
        except Exception as e:  # a journal failure must never fail the bench
            print(f"# partial-result publish failed: {e}", file=sys.stderr, flush=True)

    def load(self):
        """Best completed rung from a previous flush of THIS run, manifest-
        verified; None when absent or torn."""
        from accelerate_tpu.resilience.manifest import verify_checkpoint

        try:
            verify_checkpoint(self.root)
            with open(os.path.join(self.root, "result.json")) as f:
                return json.load(f)
        except Exception:
            return None


def _emit_error_json(error: str, detail: dict = None):
    """The driver parses the LAST JSON line on stdout; every failure path must
    leave one (round 5 regressed to ``rc=124, parsed=null`` when the probe
    window outlived the driver budget with nothing printed)."""
    rec = {
        "metric": "train_mfu",
        "value": 0.0,
        "unit": "mfu_fraction",
        "vs_baseline": 0.0,
        "error": error,
    }
    if detail:
        rec["detail"] = detail
    print(json.dumps(rec), flush=True)


def _checkpoint_probe() -> dict:
    """Measure verified-checkpoint save/verify/restore latency on a ~4M-param
    model (host-side I/O: safetensors write + manifest hash + fsync + atomic
    rename, manifest verification, full restore).  Runs on CPU — checkpoint
    I/O never touches the accelerator, and the probe must not race the tunnel."""
    import shutil
    import tempfile

    import torch

    from accelerate_tpu import Accelerator
    from accelerate_tpu.resilience import verify_checkpoint

    model = torch.nn.Sequential(*[torch.nn.Linear(1024, 1024) for _ in range(4)])
    n_params = sum(p.numel() for p in model.parameters())
    acc = Accelerator()
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    model, opt = acc.prepare(model, opt)

    tmp = tempfile.mkdtemp(prefix="atpu_bench_ckpt_")
    try:
        path = os.path.join(tmp, "ckpt")
        t0 = time.perf_counter()
        saved = acc.save_state(path, step=1)
        save_ms = (time.perf_counter() - t0) * 1e3
        t0 = time.perf_counter()
        verify_checkpoint(saved)
        verify_ms = (time.perf_counter() - t0) * 1e3
        t0 = time.perf_counter()
        acc.load_state(saved)
        load_ms = (time.perf_counter() - t0) * 1e3
        nbytes = sum(
            os.path.getsize(os.path.join(dp, f))
            for dp, _, fs in os.walk(saved)
            for f in fs
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return {
        "checkpoint": {
            "params": n_params,
            "bytes": nbytes,
            "save_ms": round(save_ms, 2),
            "verify_ms": round(verify_ms, 2),
            "restore_ms": round(load_ms, 2),
        }
    }


def _pipeline_probe() -> dict:
    """Eager-vs-fused train-step micro-benchmark on CPU (the overlapped
    execution pipeline, pipeline/train_step.py + prefetch.py): steps/s and
    dispatches/step for both paths, host-blocked ms/step with prefetch on vs
    off, and a loss-parity check.  Host-side comparison — the relative
    dispatch/overlap win is what transfers to TPU, not the absolute steps/s."""
    import tempfile

    import torch

    from accelerate_tpu import Accelerator, telemetry
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils import DataLoaderConfiguration, set_seed

    tel = telemetry.enable(dir=tempfile.mkdtemp(prefix="atpu_bench_pipeline_"))
    ACCUM = 2
    STEPS = 12  # optimizer steps per timed loop
    DIM = 256
    BATCH = 16

    class MLPWithLoss(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.net = torch.nn.Sequential(
                torch.nn.Linear(DIM, DIM),
                torch.nn.Tanh(),
                torch.nn.Linear(DIM, DIM),
                torch.nn.Tanh(),
                torch.nn.Linear(DIM, 1),
            )

        def forward(self, x, y):
            pred = self.net(x)
            return {"loss": torch.nn.functional.mse_loss(pred, y), "logits": pred}

    n_batches = ACCUM * STEPS

    def build(prefetch: int):
        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        set_seed(0)
        acc = Accelerator(
            gradient_accumulation_steps=ACCUM,
            dataloader_config=DataLoaderConfiguration(prefetch_to_device=prefetch),
        )
        model = MLPWithLoss()
        opt = torch.optim.AdamW(model.parameters(), lr=1e-3)
        rng = np.random.default_rng(0)
        data = [
            {
                "x": torch.from_numpy(rng.standard_normal((BATCH, DIM)).astype("float32")),
                "y": torch.from_numpy(rng.standard_normal((BATCH, 1)).astype("float32")),
            }
            for _ in range(n_batches)
        ]
        model, opt = acc.prepare(model, opt)
        dl = acc.prepare_data_loader(data)
        return acc, model, opt, dl

    dispatches = tel.registry.counter("pipeline.dispatches")

    def eager_loop(prefetch: int):
        acc, model, opt, dl = build(prefetch)
        losses = []

        def one_epoch(timed: bool):
            blocked = 0.0
            it = iter(dl)
            t_start = time.perf_counter()
            while True:
                t0 = time.perf_counter()
                try:
                    batch = next(it)
                except StopIteration:
                    break
                blocked += time.perf_counter() - t0
                with acc.accumulate(model):
                    out = model(**batch)
                    acc.backward(out.loss)
                    opt.step()
                    opt.zero_grad()
                    if timed:
                        losses.append(float(out.loss.detach()))
            import jax

            jax.block_until_ready(model.params)
            return time.perf_counter() - t_start, blocked

        one_epoch(timed=False)  # warmup epoch: compiles
        d0 = dispatches.value
        dt, blocked = one_epoch(timed=True)
        return {
            "steps_per_s": round(STEPS / dt, 2),
            "dispatches_per_step": (dispatches.value - d0) / STEPS,
            "host_blocked_ms_per_step": round(blocked / STEPS * 1e3, 3),
        }, losses

    def fused_loop(prefetch: int):
        acc, model, opt, dl = build(prefetch)
        step_fn = acc.make_train_step(model, opt)
        losses = []

        def one_epoch(timed: bool):
            blocked = 0.0
            window = []
            it = iter(dl)
            t_start = time.perf_counter()
            while True:
                t0 = time.perf_counter()
                try:
                    batch = next(it)
                except StopIteration:
                    break
                blocked += time.perf_counter() - t0
                window.append(batch)
                if len(window) == ACCUM:
                    out = step_fn(window)
                    if timed:
                        losses.extend(float(x) for x in np.asarray(out))
                    window = []
            import jax

            jax.block_until_ready(model.params)
            return time.perf_counter() - t_start, blocked

        one_epoch(timed=False)
        d0 = dispatches.value
        dt, blocked = one_epoch(timed=True)
        return {
            "steps_per_s": round(STEPS / dt, 2),
            "dispatches_per_step": (dispatches.value - d0) / STEPS,
            "host_blocked_ms_per_step": round(blocked / STEPS * 1e3, 3),
        }, losses

    eager_off, losses_off = eager_loop(prefetch=0)
    eager_on, losses_on = eager_loop(prefetch=2)
    fused_on, losses_fused = fused_loop(prefetch=2)
    return {
        "pipeline": {
            "accum_steps": ACCUM,
            "optimizer_steps": STEPS,
            "eager": eager_off,
            "eager_prefetch": eager_on,
            "fused_prefetch": fused_on,
            "fused_speedup": round(
                fused_on["steps_per_s"] / max(eager_off["steps_per_s"], 1e-9), 3
            ),
            "prefetch_host_blocked_ms_per_step": {
                "off": eager_off["host_blocked_ms_per_step"],
                "on": eager_on["host_blocked_ms_per_step"],
            },
            "losses_match": losses_off == losses_on == losses_fused,
        }
    }


def _zero_probe() -> dict:
    """ZeRO sharded-weight-update micro-benchmark on a forced 8-device CPU
    mesh (parallel/zero.py + the fused step): steps/s and opt-state bytes per
    chip with the sharded update OFF vs ON, a loss-parity check, and the
    one-dispatch invariant.  The HBM-per-chip shrink is the number that
    transfers to TPU; CPU steps/s only proves the sharded program isn't
    pathologically slower."""
    import tempfile

    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu import telemetry
    from accelerate_tpu.accelerator import Accelerator, JaxModel
    from accelerate_tpu.parallel import zero as zero_mod
    from accelerate_tpu.parallel.sharding import data_sharding
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils.dataclasses import ParallelismConfig

    tel = telemetry.enable(dir=tempfile.mkdtemp(prefix="atpu_bench_zero_"))
    dispatches = tel.registry.counter("pipeline.dispatches")
    NDP = jax.device_count()
    STEPS = 12
    DIM = 256
    BATCH = 16

    def build():
        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        acc = Accelerator(parallelism_config=ParallelismConfig(dp=NDP))
        params = {
            "w1": jax.random.normal(jax.random.PRNGKey(0), (DIM, DIM), jnp.float32) * 0.05,
            "b1": jax.random.normal(jax.random.PRNGKey(1), (DIM,), jnp.float32) * 0.05,
            "w2": jax.random.normal(jax.random.PRNGKey(2), (DIM, DIM), jnp.float32) * 0.05,
        }

        def apply_fn(p, x, y):
            h = jnp.tanh(x @ p["w1"] + p["b1"])
            return {"loss": jnp.mean((h @ p["w2"] - y) ** 2)}

        model, opt = acc.prepare(JaxModel(apply_fn, params), optax.adam(1e-3))
        return acc, model, opt

    def batch(acc, i):
        sh = data_sharding(acc.mesh)
        x = np.asarray(jax.random.normal(jax.random.PRNGKey(500 + i), (BATCH, DIM)), np.float32)
        y = np.asarray(jax.random.normal(jax.random.PRNGKey(600 + i), (BATCH, DIM)), np.float32)
        return {"x": jax.device_put(x, sh), "y": jax.device_put(y, sh)}

    def loop(zero: bool):
        acc, model, opt = build()
        step_fn = acc.make_train_step(model, opt, clip_norm=1.0, zero=zero)
        batches = [batch(acc, i) for i in range(STEPS)]
        losses = [float(np.asarray(step_fn(batches[0])))]  # warmup: compiles
        d0 = dispatches.value  # telemetry counter delta, as _pipeline_probe
        t0 = time.perf_counter()
        for i in range(1, STEPS):
            losses.append(float(np.asarray(step_fn(batches[i]))))
        jax.block_until_ready(model.params)
        dt = time.perf_counter() - t0
        return {
            "steps_per_s": round((STEPS - 1) / dt, 2),
            "opt_state_bytes_per_chip": zero_mod.per_chip_bytes(opt.opt_state),
            "dispatches_per_step": (dispatches.value - d0) / (STEPS - 1),
            "zero_active": step_fn.zero_active,
        }, losses

    off, losses_off = loop(False)
    on, losses_on = loop(True)
    return {
        "zero": {
            "devices": NDP,
            "optimizer_steps": STEPS,
            "off": off,
            "on": on,
            "opt_state_shrink": round(
                off["opt_state_bytes_per_chip"] / max(on["opt_state_bytes_per_chip"], 1), 2
            ),
            "losses_match": losses_off == losses_on,
        }
    }


def _pp_probe() -> dict:
    """Pipeline-schedule micro-benchmark on a forced 8-device CPU mesh
    (parallel/pipeline.py): gpipe vs interleaved (v=2) at the SAME microbatch
    count M, both through the FUSED pp train step — steps/s, dispatches/step
    via the telemetry counter delta, the analytic tick/bubble numbers, and
    the REALIZED bubble of each arm.  Two realized-bubble views: (a)
    ``measured_bubble_fraction`` = 1 - t_dense/t_arm against a dense (no-pp)
    fused step on the same mesh size — on a serializing CPU backend step
    time tracks total layer work, so this is exactly the wasted-work share
    the analytic (S-1)/(v·M+S-1) predicts; (b) the profile-scanner idle-gap
    share of the step window (``idle_fraction``) from a bounded
    ``jax.profiler`` capture — near zero on CPU (the collective-pipelining
    formulation burns bubble as garbage compute, not idle), the view that
    becomes load-bearing on a real TPU slice.  The dispatch count and the
    bubble/tick ratios are what transfer to TPU; CPU absolute steps/s do
    not."""
    import tempfile

    import jax
    import optax

    from accelerate_tpu import telemetry
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.models import llama
    from accelerate_tpu.parallel.pipeline import (
        pipeline_bubble_fraction,
        pipeline_llama_model,
        pipeline_ticks,
    )
    from accelerate_tpu.parallel.sharding import data_sharding
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.telemetry import profile_scan
    from accelerate_tpu.utils.dataclasses import ParallelismConfig, PipelineParallelPlugin

    PP = 4
    M = 4
    V = 2
    STEPS = 4
    tel = telemetry.enable(dir=tempfile.mkdtemp(prefix="atpu_bench_pp_"))
    dispatches = tel.registry.counter("pipeline.dispatches")
    cfg = llama.LlamaConfig.tiny(num_layers=8, hidden_size=64, intermediate_size=128)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, (32, 64)).astype(np.int32)

    def arm(schedule, v):
        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        if schedule == "dense":
            acc = Accelerator(parallelism_config=ParallelismConfig(dp=jax.device_count()))
            from accelerate_tpu.accelerator import JaxModel

            params = llama.init_params(cfg, jax.random.key(0))
            model = JaxModel(
                lambda p, input_ids: {"loss": llama.loss_fn(p, {"input_ids": input_ids}, cfg)},
                params,
                partition_rules=llama.PARTITION_RULES,
            )
            model, opt = acc.prepare(model, optax.adamw(1e-3))
        else:
            acc = Accelerator(
                parallelism_config=ParallelismConfig(pp=PP, dp=max(jax.device_count() // PP, 1)),
                pp_plugin=PipelineParallelPlugin(
                    pp_size=PP, num_micro_batches=M, schedule=schedule, virtual_stages=v
                ),
            )
            params = llama.init_params(cfg, jax.random.key(0))
            model, opt = acc.prepare(pipeline_llama_model(params, cfg), optax.adamw(1e-3))
        step_fn = acc.make_train_step(model, opt)
        batches = [
            {"input_ids": jax.device_put(tokens, data_sharding(acc.mesh))}
            for _ in range(STEPS)
        ]
        float(np.asarray(step_fn(batches[0])))  # warmup: compiles
        d0 = dispatches.value
        t0 = time.perf_counter()
        for b in batches[1:]:
            float(np.asarray(step_fn(b)))
        jax.block_until_ready(model.params)
        dt = time.perf_counter() - t0
        per_step_dispatch = (dispatches.value - d0) / (STEPS - 1)
        # Untimed traced replay: the idle-share audit must not tax the
        # steps/s measurement (or the dispatch tally) it rides along with.
        idle_fraction = None
        if schedule != "dense":
            trace_dir = tempfile.mkdtemp(prefix=f"atpu_bench_pp_{schedule}_")
            jax.profiler.start_trace(trace_dir)
            try:
                for b in batches[1:]:
                    float(np.asarray(step_fn(b)))
                jax.block_until_ready(model.params)
            finally:
                jax.profiler.stop_trace()
            try:
                report = profile_scan.analyze_trace_dir(trace_dir)
                idle_fraction = report.step_bubble_fraction()
                if idle_fraction is None:
                    idle_fraction = report.bubble_fraction
            except Exception as e:
                idle_fraction = f"scan failed: {str(e)[:120]}"
        return {
            "schedule": schedule,
            "virtual_stages": v,
            "steps_per_s": round((STEPS - 1) / dt, 2),
            "step_ms": round(dt / (STEPS - 1) * 1e3, 1),
            "dispatches_per_step": per_step_dispatch,
            "pp_active": step_fn.pp_active,
            "idle_fraction": idle_fraction,
        }

    dense = arm("dense", 1)
    gpipe = arm("gpipe", 1)
    inter = arm("interleaved", V)
    for block, v in ((gpipe, 1), (inter, V)):
        block["analytic_ticks"] = pipeline_ticks(PP, M, v)
        block["analytic_bubble_fraction"] = round(pipeline_bubble_fraction(PP, M, v), 4)
        # On the serializing CPU backend step time tracks total layer work,
        # so the dense fused step is the zero-bubble reference: the excess
        # over it IS the schedule's wasted-work (bubble) share.
        block["measured_bubble_fraction"] = round(
            max(0.0, 1.0 - dense["step_ms"] / max(block["step_ms"], 1e-9)), 4
        )
    return {
        "pp": {
            "devices": jax.device_count(),
            "pp_degree": PP,
            "micro_batches": M,
            "optimizer_steps": STEPS - 1,
            "dense_reference": dense,
            "gpipe": gpipe,
            "interleaved": inter,
            "interleaved_vs_gpipe_ratio": round(
                inter["steps_per_s"] / max(gpipe["steps_per_s"], 1e-9), 3
            ),
            "bubble_reduction": round(
                gpipe["measured_bubble_fraction"] - inter["measured_bubble_fraction"], 4
            ),
        }
    }


def _profile_probe() -> dict:
    """Trace-driven overlap audit of the ZeRO fused step on a forced 8-device
    CPU mesh (telemetry/profile_scan.py): captures a bounded ``jax.profiler``
    window over a few optimizer steps and attributes the device timeline —
    exposed-collective ms (comms NOT hidden behind concurrent compute),
    realized overlap fraction, and the top ops by self time.  The overlap
    fraction is the number that transfers to TPU; CPU absolute ms do not."""
    import tempfile

    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu.accelerator import Accelerator, JaxModel
    from accelerate_tpu.parallel.sharding import data_sharding
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.telemetry import profile_scan
    from accelerate_tpu.utils.dataclasses import ParallelismConfig

    NDP = jax.device_count()
    STEPS = 6
    DIM = 256
    BATCH = 16

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    acc = Accelerator(parallelism_config=ParallelismConfig(dp=NDP))
    params = {
        "w1": jax.random.normal(jax.random.PRNGKey(0), (DIM, DIM), jnp.float32) * 0.05,
        "b1": jax.random.normal(jax.random.PRNGKey(1), (DIM,), jnp.float32) * 0.05,
        "w2": jax.random.normal(jax.random.PRNGKey(2), (DIM, DIM), jnp.float32) * 0.05,
    }

    def apply_fn(p, x, y):
        h = jnp.tanh(x @ p["w1"] + p["b1"])
        return {"loss": jnp.mean((h @ p["w2"] - y) ** 2)}

    model, opt = acc.prepare(JaxModel(apply_fn, params), optax.adam(1e-3))
    step_fn = acc.make_train_step(model, opt, clip_norm=1.0, zero=NDP >= 2)
    sh = data_sharding(acc.mesh)

    def batch(i):
        x = np.asarray(jax.random.normal(jax.random.PRNGKey(500 + i), (BATCH, DIM)), np.float32)
        y = np.asarray(jax.random.normal(jax.random.PRNGKey(600 + i), (BATCH, DIM)), np.float32)
        return {"x": jax.device_put(x, sh), "y": jax.device_put(y, sh)}

    batches = [batch(i) for i in range(STEPS)]
    float(np.asarray(step_fn(batches[0])))  # warmup: compiles
    trace_dir = tempfile.mkdtemp(prefix="atpu_bench_profile_")
    jax.profiler.start_trace(trace_dir)
    try:
        for i in range(1, STEPS):
            float(np.asarray(step_fn(batches[i])))
    finally:
        jax.profiler.stop_trace()
    report = profile_scan.analyze_trace_dir(trace_dir)
    return {
        "profile": {
            "devices": NDP,
            "zero_active": step_fn.zero_active,
            "optimizer_steps": STEPS - 1,
            "window_ms": report.window_ms,
            "device_busy_ms": report.device_busy_ms,
            "compute_ms": report.compute_ms,
            "collective_ms": report.collective_ms,
            "exposed_collective_ms": report.exposed_collective_ms,
            "overlap_fraction": report.overlap_fraction,
            "steps_in_trace": len(report.steps),
            "top_ops": [
                {"name": r["name"], "bucket": r["bucket"], "self_ms": r["self_ms"]}
                for r in report.top_ops[:3]
            ],
        }
    }


def _goodput_probe() -> dict:
    """Wall-clock attribution micro-benchmark (telemetry/goodput.py): a short
    fused CPU run with a NaN-skipped step and a checkpoint save, classified
    second-by-second by the goodput ledger.  Reports the productive fraction,
    the per-category split, the fault markers, and the conservation residual
    — the CPU-tier twin of the fleet operator's first question."""
    import tempfile

    import torch

    from accelerate_tpu import Accelerator, telemetry
    from accelerate_tpu.resilience import faultinject
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.telemetry import goodput as goodput_mod
    from accelerate_tpu.utils import set_seed

    telemetry.enable(dir=tempfile.mkdtemp(prefix="atpu_bench_goodput_"))
    STEPS = 40
    DIM = 256
    BATCH = 16
    NAN_STEP = 7

    class MLPWithLoss(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.net = torch.nn.Sequential(
                torch.nn.Linear(DIM, DIM),
                torch.nn.Tanh(),
                torch.nn.Linear(DIM, 1),
            )

        def forward(self, x, y):
            pred = self.net(x)
            return {"loss": torch.nn.functional.mse_loss(pred, y), "logits": pred}

    os.environ["ACCELERATE_TPU_FAULT_NAN_STEP"] = str(NAN_STEP)
    faultinject.reload()
    try:
        import jax

        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        set_seed(0)
        acc = Accelerator()
        model = MLPWithLoss()
        opt = torch.optim.AdamW(model.parameters(), lr=1e-3)
        rng = np.random.default_rng(0)
        data = [
            {
                "x": torch.from_numpy(rng.standard_normal((BATCH, DIM)).astype("float32")),
                "y": torch.from_numpy(rng.standard_normal((BATCH, 1)).astype("float32")),
            }
            for _ in range(STEPS)
        ]
        model, opt = acc.prepare(model, opt)
        acc.enable_health_guard(max_skips=3)
        dl = acc.prepare_data_loader(data)
        step_fn = acc.make_train_step(model, opt)
        # The ledger window opens BEFORE the first (compiling) step: compile
        # badput is part of this probe's story, unlike the perf-gate row.
        ledger = goodput_mod.attach()
        skipped = []
        for i, batch in enumerate(dl):
            step_fn(batch)
            if acc.check_health(step=i + 1).skipped:
                skipped.append(i + 1)
        acc.save_state(os.path.join(tempfile.mkdtemp(prefix="atpu_bench_goodput_ck_"), "ckpt"))
        jax.block_until_ready(model.params)
        summary = ledger.summary()
        goodput_mod.detach()
    finally:
        del os.environ["ACCELERATE_TPU_FAULT_NAN_STEP"]
        faultinject.reload()

    seconds = summary["seconds"]
    return {
        "goodput": {
            "optimizer_steps": STEPS,
            "elapsed_s": round(summary["elapsed_s"], 3),
            "productive_frac": summary["goodput_fraction"],
            "seconds": {k: round(v, 4) for k, v in seconds.items()},
            "markers": summary["markers"],
            "skipped_steps": skipped,
            "conservation_error_s": summary["conservation_error_s"],
            "conservation_ok": abs(summary["conservation_error_s"]) < 1e-6,
        }
    }


def _memory_probe() -> dict:
    """HBM-ledger attribution probe (telemetry/memledger.py): who owns device
    memory after a bounded fused-step build plus a paged serving engine?
    Ranked owner bytes come from the live pytrees' actual shardings
    (deterministic shape arithmetic); on a real TPU the per-device
    conservation records also carry measured ``bytes_in_use`` and the
    unattributed residual — CPU builds report no ``memory_stats()``, so the
    block honestly carries ``stats_available: 0`` with attribution only."""
    import numpy as np
    import torch

    import jax.numpy as jnp

    import jax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import gpt2
    from accelerate_tpu.serving import ServingConfig, ServingEngine
    from accelerate_tpu.telemetry.memledger import get_memory_ledger
    from accelerate_tpu.utils import set_seed

    ledger = get_memory_ledger()
    ledger.reset()
    set_seed(0)
    dim = 128

    class MLPWithLoss(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.net = torch.nn.Sequential(
                torch.nn.Linear(dim, dim), torch.nn.Tanh(), torch.nn.Linear(dim, 1)
            )

        def forward(self, x, y):
            pred = self.net(x)
            return {"loss": torch.nn.functional.mse_loss(pred, y), "logits": pred}

    acc = Accelerator(gradient_accumulation_steps=2)
    model = MLPWithLoss()
    opt = torch.optim.AdamW(model.parameters(), lr=1e-3)
    rng = np.random.default_rng(0)
    data = [
        {
            "x": torch.from_numpy(rng.standard_normal((8, dim)).astype("float32")),
            "y": torch.from_numpy(rng.standard_normal((8, 1)).astype("float32")),
        }
        for _ in range(2)
    ]
    model, opt = acc.prepare(model, opt)
    dl = acc.prepare_data_loader(data)
    step_fn = acc.make_train_step(model, opt, zero=False)
    step_fn(list(dl))  # first call builds + registers train.params/opt_state
    jax.block_until_ready(model.params)

    cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32)
    params = gpt2.init_params(cfg, jax.random.key(0))
    engine = ServingEngine(
        gpt2.apply_cached, gpt2.init_cache, params, cfg,
        serving=ServingConfig(block_size=8, num_blocks=33, max_slots=4,
                              prefill_chunk=16, max_blocks_per_seq=8),
    )
    records = ledger.reconcile()
    snap = ledger.snapshot()
    # ``engine`` must outlive the snapshot: its GC finalizer unregisters the
    # pool reservation.
    pool_bytes = engine.stats()["pool_bytes"]
    return {
        "memory": {
            "owners": {r["owner"]: r["device_bytes"] for r in snap["owners"]},
            "attributed_bytes_per_chip": snap["attributed_bytes"],
            "host_bytes": snap["host_bytes"],
            "program_estimate_bytes": snap["program_estimate_bytes"],
            "serving_pool_bytes": pool_bytes,
            "stats_available": int(any(r.get("stats_available") for r in records)),
            "devices": records,
        }
    }


def _serving_probe() -> dict:
    """Continuous-batching serving micro-benchmark (serving/engine.py) on a
    bounded CPU run: a staggered request mix through the paged-KV engine —
    requests/s and generated tokens/s over the drain window, mean TTFT, p95
    inter-token latency, and peak block-cache occupancy.  The SLO shape
    (occupancy, dispatch counts, preemption behavior) is what transfers to
    TPU; CPU absolute latencies do not."""
    import tempfile

    import jax
    import jax.numpy as jnp

    from accelerate_tpu import telemetry
    from accelerate_tpu.models import gpt2
    from accelerate_tpu.serving import ServingConfig, ServingEngine

    tel = telemetry.enable(dir=tempfile.mkdtemp(prefix="atpu_bench_serving_"))
    cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32)
    params = gpt2.init_params(cfg, jax.random.key(0))
    engine = ServingEngine(
        gpt2.apply_cached, gpt2.init_cache, params, cfg,
        serving=ServingConfig(block_size=8, num_blocks=33, max_slots=4,
                              prefill_chunk=16, max_blocks_per_seq=8),
    )

    # Warmup request compiles the two serving programs outside the window;
    # offsets scope the engine-lifetime counters to the measured window too.
    engine.submit([1, 2, 3, 4], 2)
    engine.run(max_ticks=200)
    engine.pop_finished()
    tel.registry.reset()
    d0, p0, t0_ticks = engine.decode_dispatches, engine.prefill_dispatches, engine.ticks
    preempt0 = engine.sched.preempted_count

    N = 16
    rng = np.random.default_rng(0)
    requests = [
        (list(rng.integers(0, cfg.vocab_size, size=int(rng.integers(4, 28)))),
         int(rng.integers(2, 14)))
        for _ in range(N)
    ]
    peak_occ = 0.0
    submitted = 0
    t0 = time.perf_counter()
    while submitted < N or not engine.sched.idle():
        # Staggered arrivals: two new requests per tick while any remain.
        for _ in range(2):
            if submitted < N:
                engine.submit(*requests[submitted])
                submitted += 1
        engine.step()
        peak_occ = max(peak_occ, engine.cache.allocator.occupancy)
    wall = time.perf_counter() - t0
    done = engine.pop_finished()
    snap = tel.registry.snapshot()
    tokens = sum(c.new_tokens for c in done)

    # Overload arm: more submissions than slots + queue bound can hold, with
    # per-request deadlines — measures how the engine DEGRADES (shed rate,
    # deadline-hit rate) instead of how it cruises, plus the wall time a
    # successor needs to rebuild a dead engine's queue from the write-ahead
    # journal and finish the recovered requests (serving/journal.py).
    from accelerate_tpu.serving import AdmissionRejected

    journal_path = os.path.join(
        tempfile.mkdtemp(prefix="atpu_bench_serving_j_"), "journal.json"
    )
    overload = ServingEngine(
        gpt2.apply_cached, gpt2.init_cache, params, cfg,
        serving=ServingConfig(block_size=8, num_blocks=33, max_slots=4,
                              prefill_chunk=16, max_blocks_per_seq=8,
                              max_queue_depth=4, default_deadline_ms=300.0,
                              journal_path=journal_path),
    )
    M = 24
    burst = [
        (list(rng.integers(0, cfg.vocab_size, size=int(rng.integers(4, 20)))),
         int(rng.integers(2, 10)))
        for _ in range(M)
    ]
    shed = accepted = 0
    submitted = 0
    while submitted < M or not overload.sched.idle():
        for _ in range(6):  # burst arrivals: 6/tick vs 4 slots + 4 queue
            if submitted < M:
                try:
                    overload.submit(*burst[submitted])
                    accepted += 1
                except AdmissionRejected:
                    shed += 1
                submitted += 1
        overload.step()
    statuses = [c.status for c in overload.pop_finished()]
    expired = sum(1 for s in statuses if s == "deadline_expired")

    # Journal recovery: admit work, make partial progress, abandon the
    # engine (the SIGKILL stand-in), then time a successor's rebuild.
    victim = ServingEngine(
        gpt2.apply_cached, gpt2.init_cache, params, cfg,
        serving=ServingConfig(block_size=8, num_blocks=33, max_slots=4,
                              prefill_chunk=16, max_blocks_per_seq=8,
                              journal_path=journal_path),
    )
    for p, m in burst[:6]:
        victim.submit(p, m)
    for _ in range(3):
        victim.step()
    successor = ServingEngine(
        gpt2.apply_cached, gpt2.init_cache, params, cfg,
        serving=ServingConfig(block_size=8, num_blocks=33, max_slots=4,
                              prefill_chunk=16, max_blocks_per_seq=8,
                              journal_path=journal_path),
    )
    tr = time.perf_counter()
    recovered = successor.recover_from_journal()
    successor.run(max_ticks=2000)
    recovery_wall_ms = (time.perf_counter() - tr) * 1e3

    # Prefix-reuse arm: 16 requests sharing one 24-token system prompt, with
    # and without the content-addressed prefix cache — the TTFT drop is the
    # shared-system-prompt win (prefill collapses to the unshared suffix).
    sys_prompt = [int(t) for t in rng.integers(0, cfg.vocab_size, size=24)]
    shared_reqs = [
        (sys_prompt + [int(t) for t in rng.integers(0, cfg.vocab_size, size=4)], 8)
        for _ in range(16)
    ]

    def prefix_arm(enabled):
        eng = ServingEngine(
            gpt2.apply_cached, gpt2.init_cache, params, cfg,
            serving=ServingConfig(block_size=8, num_blocks=65, max_slots=4,
                                  prefill_chunk=8, max_blocks_per_seq=8,
                                  prefix_cache=enabled),
        )
        # Warmup traverses the same request geometry (28-token prompts, 8 new
        # tokens) so every bucketed prefill/decode program the real mix will
        # hit is compiled OUTSIDE the TTFT window — distinct random prompts,
        # so the warmup never seeds the prefix cache the arm measures.
        for _ in range(2):
            eng.submit([int(t) for t in rng.integers(0, cfg.vocab_size, size=28)], 8)
        eng.run(max_ticks=500)
        eng.pop_finished()
        for p, m in shared_reqs:
            eng.submit(p, m)
        eng.run(max_ticks=2000)
        done = eng.pop_finished()
        ttfts = [c.ttft_ms for c in done if c.ttft_ms is not None]
        return sum(ttfts) / max(len(ttfts), 1), eng

    ttft_with, cached_eng = prefix_arm(True)
    ttft_without, _ = prefix_arm(False)

    # Paged-vs-dense decode throughput: the perf-gate serving row's probe,
    # journaled here so the bench trajectory records the fast-path win too.
    from accelerate_tpu.pipeline.perf_gate import run_serving_probe, run_spec_probe

    paged_row = run_serving_probe(decode_ticks=20)

    # Speculative draft-then-verify vs plain greedy at identical geometry
    # (repeated-pattern prompts the n-gram drafter targets): acceptance,
    # tokens landed per slot-dispatch, and the p95 inter-token tail both
    # arms — journaled so the bench trajectory records the spec win too.
    spec_row = run_spec_probe()

    # KV tiering: migrated preempt-resume (host-DRAM tier) vs the re-prefill
    # fallback at identical geometry, plus raw demote/promote bandwidth —
    # journaled so the bench trajectory records the survivability win too.
    from accelerate_tpu.pipeline.perf_gate import run_tiering_probe

    tier_row = run_tiering_probe()

    # Per-request trace accounting over the staggered-mix window: blame
    # tally plus the conservation residual the tracer could not attribute
    # (serving/tracing.py) — a rising residual means the phase taxonomy is
    # leaking wall time.
    trace_stats = None
    if engine.tracer is not None and engine.tracer.completed:
        resids = [t.unattributed_ms() for t in engine.tracer.completed]
        trace_stats = {
            "requests": len(engine.tracer.completed),
            "blame": dict(sorted(engine.tracer.blame_counts.items())),
            "unattributed_ms_mean": round(sum(resids) / len(resids), 3),
            "unattributed_ms_max": round(max(resids), 3),
        }

    return {
        "serving": {
            "requests": len(done),
            "requests_per_s": round(len(done) / wall, 2),
            "tokens_per_s": round(tokens / wall, 1),
            "mean_ttft_ms": round(snap.get("serving.ttft_ms.mean", 0.0), 2),
            "p95_inter_token_ms": round(snap.get("serving.inter_token_ms.p95", 0.0), 2),
            "peak_block_occupancy": round(peak_occ, 4),
            "preempted": engine.sched.preempted_count - preempt0,
            "decode_dispatches": engine.decode_dispatches - d0,
            "prefill_dispatches": engine.prefill_dispatches - p0,
            "ticks": engine.ticks - t0_ticks,
            "pool_bytes": engine.cache.pool_bytes(),
            "overload": {
                "submitted": M,
                "shed": shed,
                "shed_rate": round(shed / M, 4),
                "deadline_expired": expired,
                "deadline_hit_rate": round(expired / max(accepted, 1), 4),
                "journal_recovered": len(recovered),
                "journal_recovery_ms": round(recovery_wall_ms, 1),
            },
            "prefix": {
                "requests": len(shared_reqs),
                "hit_rate": round(cached_eng.prefix_hits / len(shared_reqs), 4),
                "blocks_reused": cached_eng.prefix_blocks_reused,
                "cow_copies": cached_eng.cow_copies,
                "mean_ttft_with_cache_ms": round(ttft_with, 2),
                "mean_ttft_without_cache_ms": round(ttft_without, 2),
                "ttft_drop_frac": round(
                    1.0 - ttft_with / max(ttft_without, 1e-9), 4
                ),
            },
            "trace": trace_stats,
            "paged_decode": {
                "paged_steps_per_s": paged_row["serving_paged_decode_steps_per_s"],
                "dense_steps_per_s": paged_row["serving_dense_decode_steps_per_s"],
                "paged_vs_dense_ratio": paged_row["serving_paged_vs_dense_ratio"],
                "dispatches_per_tick": paged_row["serving_decode_dispatches_per_tick"],
                "gather_bytes_per_tick": round(
                    cached_eng.decode_gather_bytes / max(cached_eng.decode_dispatches, 1)
                ),
            },
            "speculative": {
                "acceptance_rate": spec_row["serving_spec_acceptance_rate"],
                "tokens_per_dispatch": spec_row["serving_spec_tokens_per_dispatch"],
                "spec_p95_inter_token_ms": spec_row["serving_spec_itl_p95_ms"],
                "greedy_p95_inter_token_ms": spec_row["serving_greedy_itl_p95_ms"],
                "spec_vs_greedy_itl_ratio": spec_row["serving_spec_vs_greedy_itl_ratio"],
                "token_identical": spec_row["serving_spec_token_identical"],
            },
            "tiering": {
                "migrated_resume_ms": tier_row["serving_migrated_resume_ms"],
                "reprefill_resume_ms": tier_row["serving_reprefill_resume_ms"],
                "migrated_vs_reprefill_ratio": tier_row[
                    "serving_migrated_vs_reprefill_ratio"
                ],
                "migrations": tier_row["serving_tier_migrations"],
                "fallback_reprefills": tier_row["serving_tier_fallback_reprefills"],
                "demote_mb_per_s": tier_row["serving_tier_demote_mb_per_s"],
                "promote_mb_per_s": tier_row["serving_tier_promote_mb_per_s"],
                "token_identical": tier_row["serving_tiering_token_identical"],
            },
        }
    }


def _health_probe() -> dict:
    """Numerical-health-guard overhead micro-benchmark (resilience/health.py):
    fused-step steps/s with the guard off vs on.  Detection lives INSIDE the
    jitted program (a ``jnp.where``-gated update on the pre-clip grad-norm
    finiteness), so the guard's only per-step host cost is floating one scalar
    — on/off must land within noise.  Also proves the skip: a NaN-poisoned
    step leaves the params bit-identical at one dispatch per step."""
    import tempfile

    import torch

    from accelerate_tpu import Accelerator, telemetry
    from accelerate_tpu.resilience import faultinject
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils import set_seed

    tel = telemetry.enable(dir=tempfile.mkdtemp(prefix="atpu_bench_health_"))
    STEPS = 100
    DIM = 256
    BATCH = 16

    class MLPWithLoss(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.net = torch.nn.Sequential(
                torch.nn.Linear(DIM, DIM),
                torch.nn.Tanh(),
                torch.nn.Linear(DIM, 1),
            )

        def forward(self, x, y):
            pred = self.net(x)
            return {"loss": torch.nn.functional.mse_loss(pred, y), "logits": pred}

    def build():
        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        set_seed(0)
        acc = Accelerator()
        model = MLPWithLoss()
        opt = torch.optim.AdamW(model.parameters(), lr=1e-3)
        rng = np.random.default_rng(0)
        data = [
            {
                "x": torch.from_numpy(rng.standard_normal((BATCH, DIM)).astype("float32")),
                "y": torch.from_numpy(rng.standard_normal((BATCH, 1)).astype("float32")),
            }
            for _ in range(STEPS)
        ]
        model, opt = acc.prepare(model, opt)
        dl = acc.prepare_data_loader(data)
        return acc, model, opt, dl

    def measure():
        import jax

        acc, model, opt, dl = build()
        acc.enable_health_guard(max_skips=3)
        step_fn = acc.make_train_step(model, opt)

        def one_epoch(guard: bool):
            t0 = time.perf_counter()
            for i, batch in enumerate(dl):
                # Both arms float the loss — every real loop logs it, and the
                # guard's premise is that it reads a second scalar from a
                # program the host was already syncing on.
                float(np.asarray(step_fn(batch)))
                if guard:
                    acc.check_health(step=i + 1)
            jax.block_until_ready(model.params)
            return time.perf_counter() - t0

        # One build, one compiled program, alternating off/on pairs: this
        # 2-core box drifts +/-50% run to run, so only a paired ratio is
        # meaningful.  Median-of-3 pairs; best epoch for the absolute rates.
        one_epoch(guard=False)  # warmup: compiles
        pairs = [(one_epoch(guard=False), one_epoch(guard=True)) for _ in range(5)]
        ratios = sorted(on / off for off, on in pairs)
        return (
            STEPS / min(off for off, _ in pairs),
            STEPS / min(on for _, on in pairs),
            ratios[len(ratios) // 2],
        )

    guard_off, guard_on, median_ratio = measure()

    # Skip proof: poison step 2 of 4, params must freeze for exactly that step.
    os.environ["ACCELERATE_TPU_FAULT_NAN_STEP"] = "2"
    faultinject.reload()
    try:
        import jax

        acc, model, opt, dl = build()
        acc.enable_health_guard(max_skips=3)
        step_fn = acc.make_train_step(model, opt)
        dispatches = tel.registry.counter("pipeline.dispatches")
        d0 = dispatches.value
        snaps, skipped = [], []
        for i, batch in enumerate(dl):
            if i == 4:
                break
            step_fn(batch)
            if acc.check_health(step=i + 1).skipped:
                skipped.append(i + 1)
            snaps.append([np.asarray(x) for x in jax.tree_util.tree_leaves(model.params)])
        frozen = all(np.array_equal(a, b) for a, b in zip(snaps[0], snaps[1]))
        moved = not all(np.array_equal(a, b) for a, b in zip(snaps[1], snaps[2]))
        one_dispatch = (dispatches.value - d0) == 4
    finally:
        del os.environ["ACCELERATE_TPU_FAULT_NAN_STEP"]
        faultinject.reload()

    return {
        "health": {
            "optimizer_steps": STEPS,
            "steps_per_s_guard_off": round(guard_off, 2),
            "steps_per_s_guard_on": round(guard_on, 2),
            "guard_overhead_pct": round((median_ratio - 1) * 100, 2),
            "skip_proof": {
                "skipped_steps": skipped,
                "params_frozen_across_skip": bool(frozen),
                "params_moved_after_skip": bool(moved),
                "one_dispatch_per_step": bool(one_dispatch),
            },
        }
    }


def _run_probe_subprocess(name: str, timeout_s: float, force_devices: int = 0):
    """One bounded CPU probe child (same contract as the rung children: last
    JSON line on stdout is the result, silence is failure).  ``name`` is the
    probe's CLI-flag stem (``--<name>-probe``); ``force_devices`` > 0 adds
    the virtual host-device XLA flag (the dp mesh the sharded-update and
    trace-attribution probes need)."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    if force_devices:
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={force_devices}"
            ).strip()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), f"--{name}-probe"],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
        )
    except subprocess.TimeoutExpired:
        return None, f"{name} probe timeout after {timeout_s:.0f}s"
    if proc.returncode != 0:
        return None, (proc.stderr or "")[-200:].replace("\n", " ")
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), None
            except ValueError:
                continue
    return None, f"no parseable {name}-probe line"


def _run_health_probe_subprocess(timeout_s: float = 240.0):
    return _run_probe_subprocess("health", timeout_s)


def _run_pipeline_probe_subprocess(timeout_s: float = 240.0):
    return _run_probe_subprocess("pipeline", timeout_s)


def _run_zero_probe_subprocess(timeout_s: float = 240.0):
    return _run_probe_subprocess("zero", timeout_s, force_devices=8)


def _run_pp_probe_subprocess(timeout_s: float = 360.0):
    return _run_probe_subprocess("pp", timeout_s, force_devices=8)


def _run_profile_probe_subprocess(timeout_s: float = 240.0):
    return _run_probe_subprocess("profile", timeout_s, force_devices=8)


def _run_checkpoint_probe_subprocess(timeout_s: float = 180.0):
    return _run_probe_subprocess("checkpoint", timeout_s)


def _run_serving_probe_subprocess(timeout_s: float = 240.0):
    return _run_probe_subprocess("serving", timeout_s)


def _run_goodput_probe_subprocess(timeout_s: float = 240.0):
    return _run_probe_subprocess("goodput", timeout_s)


def _run_memory_probe_subprocess(timeout_s: float = 240.0):
    return _run_probe_subprocess("memory", timeout_s)


def _honor_cpu_env():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from accelerate_tpu.state import honor_cpu_platform_env

    honor_cpu_platform_env()


# Last _acquire_device outcome, journaled into the bench detail block so a
# round's artifact records how hard the tunnel fought back (ROADMAP item 5:
# one flaky poll must not zero a whole round, and the fight must be visible).
_ACQUIRE_STATS = {"attempts": 0, "retries": 0, "ok": False, "detail": "never probed"}


def _acquire_device(deadline_s: float, attempt_timeout_s: float, wait_s: float):
    """Bounded device acquisition: killable-subprocess probes until the backend
    answers or the wall-clock window closes.  Each attempt is a fresh
    interpreter — the only real "backend reset" for a wedged tunnel (an
    in-process clear_backends cannot unwedge a blocked C call).

    The attempt loop is the resilience ``RetryPolicy`` (exponential backoff +
    jitter, capped at 300s between attempts, wall-clock deadline): an observed
    wedge (r4) lasted >15 min, so the window must ride it out instead of
    burning all attempts in the first minutes.  Every retry also counts into
    the shared ``resilience.retries`` telemetry counter, and the attempt/retry
    totals are journaled into the bench ``detail.device_acquire`` block.
    Returns (ok, detail, attempts)."""
    from accelerate_tpu.resilience.retry import RetryPolicy
    from accelerate_tpu.utils.device_probe import probe_device_backend

    state = {"attempts": 0, "detail": "no attempts"}

    def _probe_once():
        state["attempts"] += 1
        # First attempt with a SHORT timeout: a healthy tunnel answers in a
        # few seconds, so a wedge is detected fast instead of after 180s.
        timeout = min(60.0, attempt_timeout_s) if state["attempts"] == 1 else attempt_timeout_s
        ok, detail = probe_device_backend(timeout_s=timeout, retries=1)
        state["detail"] = detail
        if not ok:
            print(
                f"# probe attempt {state['attempts']} failed: {detail}",
                file=sys.stderr,
                flush=True,
            )
            # TimeoutError is in the policy's always-retryable set; the real
            # failure text rides along for the give-up log.
            raise TimeoutError(f"device probe failed: {detail}")
        return detail

    policy = RetryPolicy(
        tries=64,  # the deadline is the real bound; tries just backstops it
        base_delay_s=wait_s,
        max_delay_s=300.0,
        # The policy checks (elapsed + wait) against its deadline BEFORE
        # sleeping; reserve the next attempt's probe timeout so the whole
        # acquisition (old-code contract) stays inside deadline_s.
        deadline_s=max(1.0, deadline_s - attempt_timeout_s),
        # EVERY probe failure is retry-worthy here: the raised error embeds
        # the probe subprocess's raw stderr, which for a TPU held by a dying
        # process can contain RESOURCE_EXHAUSTED — default_retryable would
        # give up on exactly the transient wedge this window exists to ride
        # out (each attempt is a fresh interpreter, not a repeated alloc).
        retryable=lambda exc: True,
        label="bench.device_probe",
    )
    try:
        detail = policy.call(_probe_once)
        ok = True
    except Exception:
        detail, ok = state["detail"], False
    _ACQUIRE_STATS.update(
        {
            "attempts": _ACQUIRE_STATS["attempts"] + state["attempts"],
            "retries": _ACQUIRE_STATS["retries"] + max(0, state["attempts"] - 1),
            "ok": ok,
            "detail": detail,
        }
    )
    return ok, detail, state["attempts"]


def main():
    _honor_cpu_env()
    if "--probe" in sys.argv:
        # Probe through the killable-subprocess machinery: an in-process
        # jax.devices() on a wedged tunnel blocks inside a C call forever.
        # A probe IS a backend client — racing one against a running bench
        # is the single-client-tunnel hazard — so it try-acquires the device
        # lock first and reports "busy" (exit 2) without touching the device
        # when another bench holds it.
        from accelerate_tpu.utils.device_probe import probe_device_backend

        if os.environ.get("JAX_PLATFORMS", "").lower() != "cpu":
            from accelerate_tpu.utils.device_lock import acquire_device_lock

            if not acquire_device_lock(timeout_s=0):
                print("device busy: another bench process holds the device lock")
                sys.exit(2)
        ok, detail = probe_device_backend(
            timeout_s=float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "90")), retries=1
        )
        print(detail)
        sys.exit(0 if ok else 1)
    if "--checkpoint-probe" in sys.argv:
        print(json.dumps(_checkpoint_probe()))
        return
    if "--pipeline-probe" in sys.argv:
        print(json.dumps(_pipeline_probe()))
        return
    if "--zero-probe" in sys.argv:
        print(json.dumps(_zero_probe()))
        return
    if "--pp-probe" in sys.argv:
        print(json.dumps(_pp_probe()))
        return
    if "--profile-probe" in sys.argv:
        print(json.dumps(_profile_probe()))
        return
    if "--health-probe" in sys.argv:
        print(json.dumps(_health_probe()))
        return
    if "--serving-probe" in sys.argv:
        print(json.dumps(_serving_probe()))
        return
    if "--goodput-probe" in sys.argv:
        print(json.dumps(_goodput_probe()))
        return
    if "--memory-probe" in sys.argv:
        print(json.dumps(_memory_probe()))
        return
    if "--rung" in sys.argv or "--proof-rung" in sys.argv or "--frontier-rung" in sys.argv:
        if "--rung" in sys.argv:
            rung = LADDER[int(sys.argv[sys.argv.index("--rung") + 1])]
        elif "--proof-rung" in sys.argv:
            rung = PROOF_RUNGS[int(sys.argv[sys.argv.index("--proof-rung") + 1])]
        else:
            rung = FRONTIER_RUNGS[int(sys.argv[sys.argv.index("--frontier-rung") + 1])]
        name, d, layers, f, b, s, impl, policy = rung[:8]
        loss_impl = rung[8] if len(rung) > 8 else "dense"
        param_dtype = rung[9] if len(rung) > 9 else "f32"
        vocab = rung[10] if len(rung) > 10 else 32000
        host_opt = bool(rung[11]) if len(rung) > 11 else False
        print(
            json.dumps(
                _run(
                    name, d, layers, f, b, s, impl, policy, loss_impl, param_dtype,
                    vocab, host_opt,
                )
            )
        )
        return

    # The tunnel admits one backend client at a time; serialize with any
    # other repo bench (rung subprocesses run UNDER this lock and do not
    # re-acquire — the --rung paths above return before reaching here).
    if os.environ.get("JAX_PLATFORMS", "").lower() != "cpu":
        from accelerate_tpu.utils.device_lock import acquire_device_lock

        if not acquire_device_lock():
            _emit_error_json("device lock: timed out waiting for another bench process")
            sys.exit(1)

    # Always leave the driver a parseable line: the round-5 regression was a
    # 40-min probe window outliving the driver's own budget — rc=124,
    # parsed=null, round zeroed.  A daemon watchdog emits a final JSON and
    # exits before any external kill can land, and SIGTERM (the driver's
    # cooperative kill) does the same.  Once the HEADLINE measurement lands
    # (proof/frontier rungs still running) the emergency line is that real
    # result, not a zero — a budget hit late in the run must never discard a
    # valid number.
    landed: dict = {}
    journal = _PartialResults()
    journal.clear()

    def _emergency_exit(reason: str):
        if landed:
            rec = dict(landed)
            rec["detail"] = dict(rec["detail"], truncated=reason)
            print(json.dumps(rec), flush=True)
            os._exit(0)
        # Nothing landed in-memory: a partial published earlier in THIS run
        # (manifest-verified) still beats a zero.
        partial = journal.load()
        if partial and "metric" in partial:
            rec = dict(partial)
            rec["detail"] = dict(rec.get("detail") or {}, truncated=reason)
            print(json.dumps(rec), flush=True)
            os._exit(0)
        _emit_error_json(reason)
        os._exit(1)

    total_budget = float(os.environ.get("BENCH_TOTAL_BUDGET_S", "1800"))
    if total_budget > 0:
        import threading

        _watchdog = threading.Timer(
            total_budget,
            lambda: _emergency_exit(f"bench wall-clock budget {total_budget:.0f}s exceeded"),
        )
        _watchdog.daemon = True
        _watchdog.start()
    import signal

    # The driver's cooperative kill routes through the library's
    # PreemptionGuard (one signal code path for bench AND training loops);
    # the callback still emits the emergency JSON line before exiting.
    from accelerate_tpu.resilience import PreemptionGuard

    _guard = PreemptionGuard(signals=(signal.SIGTERM,), coordinated=False)
    _guard.add_callback(lambda signum: _emergency_exit("SIGTERM received (driver budget?)"))
    _guard.install()

    # Fast-fail (then retry, bounded) when the device backend is unreachable
    # (e.g. wedged TPU tunnel).  Probes MUST be subprocesses: backend init
    # blocks inside a C call, which a SIGALRM-based timeout cannot interrupt.
    # The window defaults WELL UNDER the driver budget (riding out a >15 min
    # wedge belongs to manual runs via BENCH_PROBE_WINDOW_S; a driver run that
    # records an explicit probe-failure JSON beats one killed at rc=124 with
    # no output at all).
    probe_window = float(os.environ.get("BENCH_PROBE_WINDOW_S", "600"))
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "120"))
    probe_wait = float(os.environ.get("BENCH_PROBE_WAIT_S", "30"))
    ok, detail, attempts = _acquire_device(
        deadline_s=probe_window,
        attempt_timeout_s=probe_timeout,
        wait_s=probe_wait,
    )
    if not ok:
        _emit_error_json(f"device backend unreachable after {attempts} probes: {detail}")
        sys.exit(1)
    print(f"# bench devices: {detail} ({attempts} probe attempts)", file=sys.stderr)

    def _cfg_str(rung):
        name, _, _, _, batch, seq, impl, policy = rung[:8]
        for extra in rung[8:]:
            policy = f"{policy}/{extra}"
        return f"{name}/b{batch}/s{seq}/{impl}/{policy}"

    def _device_trouble(err: str) -> bool:
        """Rung failures that mean the TUNNEL died (vs. the config OOMing):
        burning the next rung would waste 480s per attempt against a wedge —
        reacquire first.  RESOURCE_EXHAUSTED / compile errors are NOT device
        trouble; the ladder's next rung is the right response to those."""
        if not err:
            return False
        e = err.lower()
        if "resource_exhausted" in e or "out of memory" in e:
            return False
        return any(
            s in e
            for s in ("timeout", "unreachable", "unavailable", "deadline", "no parseable")
        )

    rung_timeout = int(float(os.environ.get("BENCH_RUNG_TIMEOUT_S", "480")))
    result = None
    rung_log = []
    rung_cfg = None
    tunnel_lost = False
    try:  # fresh side file per run (it appends during the frontier pass)
        os.unlink("BENCH_frontier_live.json")
    except OSError:
        pass
    for i, rung in enumerate(LADDER):
        result, err = _run_rung_subprocess(i, timeout_s=rung_timeout)
        # Per-rung emission: a later crash can no longer zero the round — the
        # outcome of every attempted rung is in the final JSON and on stderr.
        status = "ok" if result is not None else err
        rung_log.append({"rung": i, "config": _cfg_str(rung), "status": status})
        print(f"# rung {i} {rung_log[-1]['config']}: {status}", file=sys.stderr, flush=True)
        if result is not None:
            rung_cfg = rung_log[-1]["config"]
            break
        if _device_trouble(err):
            ok2, d2, n2 = _acquire_device(probe_window, probe_timeout, probe_wait)
            rung_log.append(
                {"rung": f"reacquire-after-{i}", "status": "ok" if ok2 else d2, "probes": n2}
            )
            print(
                f"# reacquire after rung {i}: {'ok' if ok2 else d2} ({n2} probes)",
                file=sys.stderr,
                flush=True,
            )
            if not ok2:
                tunnel_lost = True
                break
            # Tunnel answered again: retry the SAME rung once before moving
            # on — its failure may have been the wedge, not the config.
            result, err = _run_rung_subprocess(i, timeout_s=rung_timeout)
            status = "ok" if result is not None else err
            rung_log.append({"rung": f"{i}-retry", "config": _cfg_str(rung), "status": status})
            print(f"# rung {i} retry: {status}", file=sys.stderr, flush=True)
            if result is not None:
                rung_cfg = _cfg_str(rung)
                break
    if result is None:
        _emit_error_json(
            "tunnel lost mid-run" if tunnel_lost else "all rungs failed",
            detail={"rungs": rung_log},
        )
        sys.exit(1)

    # Headline landed: from here on the emergency line carries this number.
    landed.update(
        {
            "metric": "train_mfu",
            "value": round(result["mfu"], 4),
            "unit": "mfu_fraction",
            "vs_baseline": round(result["mfu"] / 0.45, 4),
            "detail": {
                "config": result["config"],
                "rung": rung_cfg,
                "params": result["params"],
                "tokens_per_sec": round(result["tokens_per_sec"], 1),
                "step_ms": round(result["step_ms"], 2),
                # Device-acquisition fight journal (retrying() policy): how
                # many probes/backoff retries this round burned before the
                # backend answered — the r1/r2/r4/r5 flake story, measured.
                "device_acquire": dict(_ACQUIRE_STATS),
                **({"telemetry": result["telemetry"]} if "telemetry" in result else {}),
                **({"introspect": result["introspect"]} if "introspect" in result else {}),
            },
        }
    )
    # ... and the on-disk journal carries it past even a SIGKILL.
    journal.publish(landed)

    # HBM-bound proof: run the >=1B-param rungs after the headline so the
    # round artifact carries MFU evidence off the smallest model.  First
    # success wins; failures are logged but never zero the headline.
    proof = None
    proof_cfg = None
    for i, rung in enumerate(PROOF_RUNGS):
        proof, err = _run_rung_subprocess(i, timeout_s=rung_timeout, flag="--proof-rung")
        if proof is None and _device_trouble(err):
            # The headline is already landed; still worth one bounded
            # reacquire so the HBM-bound proof rides out a transient wedge.
            ok2, d2, n2 = _acquire_device(min(probe_window, 1200.0), probe_timeout, probe_wait)
            rung_log.append(
                {"rung": f"proof-reacquire-{i}", "status": "ok" if ok2 else d2, "probes": n2}
            )
            if not ok2:
                rung_log.append({"rung": f"proof-{i}", "config": _cfg_str(rung), "status": err})
                break
            proof, err = _run_rung_subprocess(i, timeout_s=rung_timeout, flag="--proof-rung")
        # A parseable-but-foreign JSON line (library noise) must not crash the
        # already-measured headline below — require the result keys.
        if proof is not None and not all(
            k in proof for k in ("mfu", "params", "tokens_per_sec", "step_ms")
        ):
            proof, err = None, "unrecognized result payload"
        status = "ok" if proof is not None else err
        cfg_str = _cfg_str(rung)
        rung_log.append({"rung": f"proof-{i}", "config": cfg_str, "status": status})
        print(f"# proof rung {i} {cfg_str}: {status}", file=sys.stderr, flush=True)
        if proof is not None:
            proof_cfg = cfg_str
            break
    # Frontier: unmeasured candidates AFTER the headline+proof landed — every
    # outcome logged (never replaces the headline), wall-clock bounded, and
    # appended to a side file that survives a mid-run kill.
    frontier = []
    frontier_budget = float(os.environ.get("BENCH_FRONTIER_BUDGET_S", "900"))
    t_frontier = time.monotonic()
    for i, rung in enumerate(FRONTIER_RUNGS):
        if time.monotonic() - t_frontier > frontier_budget:
            frontier.append({"config": _cfg_str(rung), "status": "skipped (budget)"})
            continue
        fres, err = _run_rung_subprocess(i, timeout_s=rung_timeout, flag="--frontier-rung")
        if fres is not None and not all(
            k in fres for k in ("mfu", "params", "tokens_per_sec", "step_ms")
        ):
            fres, err = None, "unrecognized result payload"
        entry = {"config": _cfg_str(rung), "status": "ok" if fres is not None else err}
        if fres is not None:
            entry.update(
                mfu=round(fres["mfu"], 4),
                tokens_per_sec=round(fres["tokens_per_sec"], 1),
                step_ms=round(fres["step_ms"], 2),
            )
        frontier.append(entry)
        print(f"# frontier {i} {entry['config']}: {entry['status']}", file=sys.stderr, flush=True)
        try:
            with open("BENCH_frontier_live.json", "a") as f:
                f.write(json.dumps(entry) + "\n")
        except OSError:
            pass
        if fres is None and _device_trouble(err):
            break  # tunnel gone; headline is safe, stop burning rung slots

    # Checkpoint save/restore latency (resilience subsystem): CPU subprocess,
    # cheap, never zeroes the headline — a failure is recorded as a status.
    ckpt_block = None
    if os.environ.get("BENCH_CHECKPOINT_PROBE", "1") != "0":
        ckpt_probe, ckpt_err = _run_checkpoint_probe_subprocess()
        ckpt_block = ckpt_probe["checkpoint"] if ckpt_probe else {"status": ckpt_err}
        print(f"# checkpoint probe: {ckpt_block}", file=sys.stderr, flush=True)

    # Overlapped-pipeline probe (eager vs fused dispatch counts + prefetch
    # host-blocked time): CPU subprocess, never zeroes the headline.
    pipeline_block = None
    if os.environ.get("BENCH_PIPELINE_PROBE", "1") != "0":
        pipe_probe, pipe_err = _run_pipeline_probe_subprocess()
        pipeline_block = pipe_probe["pipeline"] if pipe_probe else {"status": pipe_err}
        print(f"# pipeline probe: {pipeline_block}", file=sys.stderr, flush=True)

    # Numerical-health-guard overhead (resilience/health.py): CPU subprocess,
    # never zeroes the headline — detection is in-program, so guard on/off
    # must be within noise.
    health_block = None
    if os.environ.get("BENCH_HEALTH_PROBE", "1") != "0":
        health_probe, health_err = _run_health_probe_subprocess()
        health_block = health_probe["health"] if health_probe else {"status": health_err}
        print(f"# health probe: {health_block}", file=sys.stderr, flush=True)

    # ZeRO sharded-update probe (parallel/zero.py): opt-state bytes/chip and
    # steps/s with the sharded update on vs off, on a forced 8-device CPU
    # mesh.  CPU subprocess, never zeroes the headline.
    zero_block = None
    if os.environ.get("BENCH_ZERO_PROBE", "1") != "0":
        zero_probe, zero_err = _run_zero_probe_subprocess()
        zero_block = zero_probe["zero"] if zero_probe else {"status": zero_err}
        print(f"# zero probe: {zero_block}", file=sys.stderr, flush=True)

    # Pipeline-schedule probe (parallel/pipeline.py): gpipe vs interleaved
    # fused pp steps at fixed M on a forced 8-device CPU mesh — steps/s,
    # dispatches/step, analytic + measured (profile-scanner idle share)
    # bubble fractions.  CPU subprocess, never zeroes the headline.
    pp_block = None
    if os.environ.get("BENCH_PP_PROBE", "1") != "0":
        pp_probe, pp_err = _run_pp_probe_subprocess()
        pp_block = pp_probe["pp"] if pp_probe else {"status": pp_err}
        print(f"# pp probe: {pp_block}", file=sys.stderr, flush=True)

    # Trace-attribution probe (telemetry/profile_scan.py): exposed-collective
    # ms + realized overlap of the ZeRO fused step from a bounded jax.profiler
    # capture on a forced 8-device CPU mesh.  CPU subprocess, never zeroes the
    # headline.
    profile_block = None
    if os.environ.get("BENCH_PROFILE_PROBE", "1") != "0":
        prof_probe, prof_err = _run_profile_probe_subprocess()
        profile_block = prof_probe["profile"] if prof_probe else {"status": prof_err}
        print(f"# profile probe: {profile_block}", file=sys.stderr, flush=True)

    # Continuous-batching serving probe (serving/engine.py): requests/s, mean
    # TTFT, p95 inter-token latency and peak block-cache occupancy of a
    # staggered request mix through the paged-KV engine.  CPU subprocess,
    # never zeroes the headline.
    serving_block = None
    if os.environ.get("BENCH_SERVING_PROBE", "1") != "0":
        serving_probe, serving_err = _run_serving_probe_subprocess()
        serving_block = serving_probe["serving"] if serving_probe else {"status": serving_err}
        print(f"# serving probe: {serving_block}", file=sys.stderr, flush=True)

    # Goodput-attribution probe (telemetry/goodput.py): what fraction of a
    # short fused run's wall clock was productive step compute, and where the
    # rest (compile, checkpoint, input wait, health-skip replay) went.  CPU
    # subprocess, never zeroes the headline.
    goodput_block = None
    if os.environ.get("BENCH_GOODPUT_PROBE", "1") != "0":
        goodput_probe, goodput_err = _run_goodput_probe_subprocess()
        goodput_block = goodput_probe["goodput"] if goodput_probe else {"status": goodput_err}
        print(f"# goodput probe: {goodput_block}", file=sys.stderr, flush=True)

    # HBM-ledger attribution probe (telemetry/memledger.py): ranked owner
    # bytes for a bounded fused step + serving engine, with per-device
    # conservation records where the backend reports memory_stats().  CPU
    # subprocess, never zeroes the headline.
    memory_block = None
    if os.environ.get("BENCH_MEMORY_PROBE", "1") != "0":
        memory_probe, memory_err = _run_memory_probe_subprocess()
        memory_block = memory_probe["memory"] if memory_probe else {"status": memory_err}
        print(f"# memory probe: {memory_block}", file=sys.stderr, flush=True)

    detail = {
        "config": result["config"],
        "rung": rung_cfg,
        "params": result["params"],
        "tokens_per_sec": round(result["tokens_per_sec"], 1),
        "step_ms": round(result["step_ms"], 2),
        "loss": round(result["loss"], 4),
        "rungs": rung_log,
    }
    if "telemetry" in result:
        detail["telemetry"] = result["telemetry"]
    if "introspect" in result:
        detail["introspect"] = result["introspect"]
    if frontier:
        detail["frontier"] = frontier
    if ckpt_block is not None:
        detail["checkpoint"] = ckpt_block
    if pipeline_block is not None:
        detail["pipeline"] = pipeline_block
    if health_block is not None:
        detail["health"] = health_block
    if zero_block is not None:
        detail["zero"] = zero_block
    if pp_block is not None:
        detail["pp"] = pp_block
    if profile_block is not None:
        detail["profile"] = profile_block
    if serving_block is not None:
        detail["serving"] = serving_block
    if goodput_block is not None:
        detail["goodput"] = goodput_block
    if memory_block is not None:
        detail["memory"] = memory_block
    if proof is not None:
        detail["hbm_bound_proof"] = {
            "config": proof_cfg,
            "params": proof["params"],
            "mfu": round(proof["mfu"], 4),
            "vs_baseline": round(proof["mfu"] / 0.45, 4),
            "tokens_per_sec": round(proof["tokens_per_sec"], 1),
            "step_ms": round(proof["step_ms"], 2),
        }
        if "telemetry" in proof:
            detail["hbm_bound_proof"]["telemetry"] = proof["telemetry"]
    # Re-publish the journal with the full detail (proof/frontier/probes
    # attached) so the on-disk partial matches the final line.
    journal.publish(
        {
            "metric": "train_mfu",
            "value": round(result["mfu"], 4),
            "unit": "mfu_fraction",
            "vs_baseline": round(result["mfu"] / 0.45, 4),
            "detail": detail,
        }
    )
    print(
        json.dumps(
            {
                "metric": "train_mfu",
                "value": round(result["mfu"], 4),
                "unit": "mfu_fraction",
                "vs_baseline": round(result["mfu"] / 0.45, 4),
                "detail": detail,
            }
        )
    )


if __name__ == "__main__":
    # Rung/probe children must NOT print an error JSON on failure — the
    # parent scans their stdout for the last JSON line and would mistake it
    # for a measurement; their silence IS the failure signal.
    _is_child = any(
        flag in sys.argv
        for flag in (
            "--rung",
            "--proof-rung",
            "--frontier-rung",
            "--probe",
            "--checkpoint-probe",
            "--pipeline-probe",
            "--health-probe",
            "--zero-probe",
            "--pp-probe",
            "--profile-probe",
            "--serving-probe",
            "--goodput-probe",
        )
    )
    try:
        main()
    except SystemExit:
        raise
    except Exception as e:
        if not _is_child:
            _emit_error_json(f"unhandled exception: {type(e).__name__}: {e}")
        raise
