# Test-suite splits mirroring the reference Makefile:25-77.

.PHONY: test test-quick test_core test_big_modeling test_cli test_fsdp test_tp test_examples test_kernels bench telemetry-smoke introspect-smoke resilience-smoke pipeline-smoke health-smoke flightrec-smoke zero-smoke pp-smoke profile-smoke serving-smoke spec-smoke serving-trace-smoke elastic-smoke chaos-smoke serving-chaos-smoke tiering-chaos-smoke fleet-chaos-smoke goodput-smoke memory-smoke perf-gate

PYTEST = python -m pytest -q

test: test-quick telemetry-smoke introspect-smoke resilience-smoke pipeline-smoke health-smoke flightrec-smoke zero-smoke pp-smoke profile-smoke serving-smoke spec-smoke serving-trace-smoke elastic-smoke chaos-smoke serving-chaos-smoke tiering-chaos-smoke fleet-chaos-smoke goodput-smoke memory-smoke perf-gate
	$(PYTEST) tests/

# <5 min tier (VERDICT r5 item 6): oracles, state, sharding-spec/mesh,
# resilience + health unit tests — no subprocess smokes.  First stage of
# `make test` so fast failures surface before the multi-minute suites run.
test-quick:
	$(PYTEST) tests/test_oracles.py tests/test_state.py tests/test_mesh_matrix.py \
	  tests/test_resilience.py tests/test_health.py -m 'not slow'

# 3-step CPU training loop with telemetry ON; asserts the JSONL trace is
# non-empty and parseable (docs/usage_guides/telemetry.md).
telemetry-smoke:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.telemetry.smoke

# 2-step CPU loop on a forced dp=2 mesh with ACCELERATE_TPU_INTROSPECT=1;
# asserts the comms-ledger JSON parses and reports >= 1 collective
# (docs/package_reference/introspect.md).
introspect-smoke:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.telemetry.introspect_smoke

# Kill-and-resume proof: SIGTERMs a CPU training run mid-step (fault
# injection), asserts the PreemptionGuard wrote a manifest-complete verified
# checkpoint, and a fresh process resumes to bit-exact loss continuation
# (docs/usage_guides/resilience.md).  QUARANTINED: runs serialized with ONE
# bounded retry via smoke_retry — the smoke has a pre-existing environmental
# flake (XLA-CPU corruption under parallel machine load, repro'd on base
# trees); the retry is loud (stderr + smoke.retried event), never silent.
resilience-smoke:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.resilience.smoke_retry \
	  --label resilience-smoke -- python -m accelerate_tpu.resilience.smoke

# Eager vs fused train step on CPU: asserts the dispatch-count gauge shows
# exactly 1 dispatch per accumulation window on the fused path (3 x accum on
# eager), bit-exact losses/params between the two, and prefetch ordering
# (docs/usage_guides/performance.md).
pipeline-smoke:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.pipeline.smoke

# ZeRO sharded-update proof on an 8-device CPU dryrun mesh: bit-exact losses
# ZeRO on/off (binding clip), the comms ledger shows reduce-scatter +
# all-gather replacing the dp grad all-reduce, and opt-state bytes/chip
# shrink dp-fold (docs/usage_guides/performance.md).
zero-smoke:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.parallel.zero_smoke

# Fused pipeline-parallel proof on an 8-device CPU dryrun mesh: pp=2 x v=2
# llama through make_train_step — gpipe/interleaved loss equivalence, exactly
# ONE dispatch per optimizer step for both schedules, and the executed
# collective-permute ledger (per-tick bytes x ticks, invariant in v)
# (docs/usage_guides/performance.md, "Pipeline schedules").
pp-smoke:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.pipeline.pp_smoke

# Numerical-health proof: NaN-poisons a CPU run's gradients (fault
# injection), asserts the in-program gate skips the step with bit-identical
# params at ONE dispatch/step, and that a 3x-consecutive-NaN run rewinds to
# the last verified checkpoint and continues bit-exact vs a clean resume
# (docs/usage_guides/resilience.md).
health-smoke:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.resilience.health_smoke

# Elastic-resume proof: a checkpoint saved on a dp=8 mesh with the ZeRO
# sharded update resumes on dp=4, dp=2 x fsdp=2, and a ZeRO-off mesh —
# params + opt state bit-identical after the GSPMD relayout (SHA-256 state
# digest), the manifest topology record validated leaf-by-leaf, and 4
# post-resume training steps run on each new mesh
# (docs/usage_guides/resilience.md, "Elastic resume").  Quarantined like
# resilience-smoke: same multi-subprocess XLA-CPU-under-load workload, same
# environmental flake class — one loud bounded retry via smoke_retry.
elastic-smoke:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.resilience.smoke_retry \
	  --label elastic-smoke -- python -m accelerate_tpu.resilience.elastic_smoke

# Chaos campaign: a seeded schedule of faults (SIGTERM mid-step, sticky torn
# checkpoint writes, synthetic OOM, NaN-poisoned gradients) across repeated
# kill->resume cycles that CHANGE the mesh shape between lives.  Asserts
# zero torn publishes, bit-identical state handoff across topology changes,
# same-topology bit-exact losses vs an unkilled reference, and a final
# manifest-complete verified checkpoint (docs/usage_guides/resilience.md).
# Quarantined with one loud bounded retry (see resilience-smoke note).
chaos-smoke:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.resilience.smoke_retry \
	  --label chaos-smoke -- python -m accelerate_tpu.resilience.chaos

# Black-box proof: SIGTERMs a flight-recorder-enabled CPU training run
# mid-step, asserts the crash-safe JSONL snapshot on disk carries the final
# step's events + the signal, that the chained PreemptionGuard still wrote
# its manifest-complete checkpoint, and that telemetry.report renders a
# postmortem from the snapshot (docs/package_reference/flightrec.md).
flightrec-smoke:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.telemetry.flightrec_smoke

# Trace-attribution proof on an 8-device CPU mesh: captures a jax.profiler
# trace of the fused ZeRO step, asserts the scanner reconstructs a timeline
# with >= 1 collective bucket, a finite realized-overlap fraction and
# exposed-collective <= total-collective ms, and that the SAME parser passes
# offline on the committed fixture with no JAX devices
# (docs/package_reference/profile.md).
profile-smoke:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.telemetry.profile_smoke

# Continuous-batching proof on an 8-device CPU mesh: a staggered request mix
# through the paged-KV serving engine (pool tight enough to force
# preemption) must produce token-identical greedy outputs to the offline
# generate_loop per request, keep the fused decode step at <= 1 dispatch per
# tick (telemetry counter delta), and land the serving.* SLO metrics in the
# telemetry report (docs/usage_guides/serving.md).
serving-smoke:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.serving.smoke

# Speculative-decode proof on an 8-device CPU mesh: pattern-heavy and random
# prompts through a spec_tokens=3 engine (draft-then-verify inside the fused
# decode dispatch) must stay token-identical to the offline generate_loop,
# land acceptance_rate > 0 with > 1 token per slot-dispatch, keep every
# decode tick on the ONE fixed k+1 window program per bucket (spec.rounds ==
# decode dispatches), and leave the KV pool fully free after drain
# (docs/usage_guides/serving.md, "Speculative decoding").  One loud bounded
# retry via smoke_retry (subprocess XLA-CPU workload, same flake class as
# resilience-smoke).
spec-smoke:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.resilience.smoke_retry \
	  --label spec-smoke -- python -m accelerate_tpu.serving.spec_smoke

# Per-request trace proof: a forced-slow request mix (injected queue delay +
# injected preemption) must be blamed on the right phase by the trace
# decomposer with the conservation invariant holding per request, the Chrome
# export must re-parse through telemetry/timeline.py with slot/request
# tracks intact, a live mid-flight /debug/requests + /debug/blocks +
# /healthz scrape must succeed (404s unchanged), the offline report block
# must render from the trace JSONL alone, and steady-state decode throughput
# with tracing on must stay close to off
# (docs/package_reference/serving_tracing.md).
serving-trace-smoke:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.serving.trace_smoke

# Serving-under-fire proof: a seeded campaign mixing an overload burst
# (exact shed count), a NaN-poisoned request (in-program detection ->
# quarantine while other slots decode bit-identically), a deadline storm
# (queued requests shed before any prefill chunk), a SIGTERM drain, and a
# SIGKILL followed by TWO write-ahead-journal recoveries.  Every surviving
# request's tokens must equal the offline generate_loop oracle, the block
# allocator must leak nothing, and shed/expired/quarantined counts must
# match the plan (docs/usage_guides/serving.md, "Overload & failure
# handling").  Quarantined with one loud bounded retry (subprocess XLA-CPU
# workload, same flake class as resilience-smoke).
serving-chaos-smoke:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.resilience.smoke_retry \
	  --label serving-chaos-smoke -- python -m accelerate_tpu.serving.chaos

# KV-tiering-under-fire proof: a pool tight enough that every life preempts,
# with the host-DRAM tier on.  Arms: a memory-pressure life (preemption
# demotes KV blocks to host, re-admission promotes them back — real
# migrations, ZERO re-prefill dispatches on migrated resumes), a host-full
# life (SERVING_HOST_FULL fault forces the fallback re-prefill path), a
# SIGKILL landed at the instant a request's blocks sit in host DRAM (the
# journal must record "host" residency), and a journal recovery that
# finishes everything.  Every output token-identical to generate_loop, zero
# block leaks in either tier (docs/usage_guides/serving.md, "KV tiering &
# memory pressure").  Quarantined with one loud bounded retry (subprocess
# XLA-CPU workload, same flake class as resilience-smoke).
tiering-chaos-smoke:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.resilience.smoke_retry \
	  --label tiering-chaos-smoke -- \
	  python -m accelerate_tpu.serving.chaos --campaign tiering

# Multi-process fleet campaign: a REAL 4-process localhost jax.distributed
# cluster (gloo CPU collectives, hybrid dcn_dp mesh) launched and babysat by
# the FleetSupervisor.  Arms: SIGKILL one worker mid-step (supervisor reaps
# the wedged survivors within the grace bound + writes a rank-merged
# postmortem), SIGTERM one rank (coordinated drain: every rank agrees on the
# SAME stop step over the coordinator KV service and ONE verified checkpoint
# lands), wedge one worker without dying (heartbeat-stall detection), and a
# SIGKILL under --elastic (relaunch at world 3; the resumed state digest must
# be BIT-IDENTICAL to the unkilled 4-process reference at the resume step)
# (docs/usage_guides/multihost.md).  Quarantined with one loud bounded retry
# (multi-subprocess XLA-CPU workload, same flake class as resilience-smoke).
fleet-chaos-smoke:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.resilience.smoke_retry \
	  --label fleet-chaos-smoke -- python -m accelerate_tpu.resilience.chaos --mode fleet

# Goodput-accounting proof: a short chaos-style CPU run with every badput
# source injected (NaN health-skip, torn checkpoint write, synthetic OOM,
# SIGTERM) — asserts the wall-clock ledger's conservation invariant
# (categories sum to elapsed time within epsilon), that each injected fault
# class lands in its correct badput category, and that the Prometheus
# endpoint serves (and the atomic snapshot file holds) valid text exposition
# with the goodput.* gauges (docs/package_reference/goodput.md).
goodput-smoke:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.telemetry.goodput_smoke

# HBM-ledger smoke (telemetry/memledger.py) on an 8-device CPU dryrun mesh:
# exact shard-level attribution, the per-device conservation contract with an
# injected allocator view (negative residual exposed, CPU stats honestly
# absent), a fault-injected RESOURCE_EXHAUSTED whose postmortem blames the
# planted owner, and the memory.* scrape + /debug/memory endpoint.
memory-smoke:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.telemetry.memledger_smoke

# CPU-tier perf-regression gate: eager-vs-fused probe judged against the
# committed baseline (benchmarks/perf_baseline_cpu.json) — dispatches/step
# must stay 1 on the fused path, the fused-vs-eager steps/s ratio above its
# floor, host-blocked ms under its ceiling.  Also run inside tier-1 by
# tests/test_perf_gate.py (docs/usage_guides/performance.md).
perf-gate:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.pipeline.perf_gate

# Everything except big-modeling / engine dialects / CLI / examples.
test_core:
	$(PYTEST) tests/ --ignore=tests/test_big_modeling.py \
	  --ignore=tests/test_engine_dialects.py --ignore=tests/test_cli_commands.py \
	  --ignore=tests/test_cli_launchers.py --ignore=tests/test_examples.py \
	  --ignore=tests/test_by_feature_examples.py

test_big_modeling:
	$(PYTEST) tests/test_big_modeling.py tests/test_quantization.py tests/test_native_io.py

test_cli:
	$(PYTEST) tests/test_cli_commands.py tests/test_cli_launchers.py

test_fsdp:
	$(PYTEST) tests/test_llama.py tests/test_checkpoint_formats.py tests/test_engine_dialects.py

test_tp:
	$(PYTEST) tests/test_llama_sp.py tests/test_ulysses.py tests/test_pipeline.py

test_examples:
	$(PYTEST) tests/test_examples.py tests/test_by_feature_examples.py

test_kernels:
	$(PYTEST) tests/test_flash_attention.py tests/test_pallas_attention.py \
	  tests/test_ring_attention.py tests/test_ulysses.py tests/test_chunked_ce.py \
	  tests/test_moe.py tests/test_fp8.py

bench:
	python bench.py

# C++ offload streamer (auto-built on first use by utils/native_io.py; this
# target is the explicit form the docs reference).
.PHONY: native
native:
	g++ -O3 -march=native -shared -fPIC -pthread \
	  accelerate_tpu/_native/tensorstore.cpp -o accelerate_tpu/_native/libtensorstore.so
